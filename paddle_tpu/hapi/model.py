"""paddle.Model — the high-level train/eval/predict API.

Reference: python/paddle/hapi/model.py:906 (Model), DynamicGraphAdapter
(model.py:704). TPU-native: train_batch dispatches to a fused jitted
TrainStep (forward+backward+optimizer in one XLA program) when possible —
the replacement for the reference's program+executor adapter — and falls back
to the eager tape when AMP-with-scaler or custom flows demand it.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

from .. import amp as amp_mod
from ..framework import autograd
from ..framework.tensor import Tensor
from ..io import DataLoader, Dataset
from ..jit import TrainStep
from ..metric import Metric
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _tensorize(batch):
    out = []
    for b in _to_list(batch):
        out.append(b if isinstance(b, Tensor) else Tensor(np.asarray(b)))
    return out


class StaticGraphAdapter:
    """Model's static-mode engine (reference: hapi/model.py StaticGraphAdapter
    :~280): builds train/eval/predict Programs once, then every batch is one
    Executor.run of the corresponding compiled program. Programs are built
    lazily from the first batch's shapes (or the Model's InputSpec list) with
    a None batch dim, so batch size may vary."""

    def __init__(self, model: "Model"):
        self.model = model
        self._progs = {}
        self._exe = None

    def _specs_from(self, tensors, given):
        if given:
            return [(s.name or f"x{i}", [None] + list(s.shape)[1:],
                     str(np.dtype(s.dtype)))
                    for i, s in enumerate(_to_list(given))]
        return [(f"var_{id(self)}_{i}", (None,) + tuple(t._value.shape[1:]),
                 str(t._value.dtype))
                for i, t in enumerate(tensors)]

    def _build(self, mode, inputs, labels):
        from .. import static

        m = self.model
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            in_specs = self._specs_from(inputs, m._inputs)
            lb_specs = self._specs_from(labels, m._labels) if labels else []
            in_vars = [static.data(f"in_{i}_{n}", shape, dtype)
                       for i, (n, shape, dtype) in enumerate(in_specs)]
            lb_vars = [static.data(f"lb_{i}_{n}", shape, dtype)
                       for i, (n, shape, dtype) in enumerate(lb_specs)]
            m.network.train() if mode == "train" else m.network.eval()
            outs = _to_list(m.network(*in_vars))
            fetch = list(outs)
            loss = None
            if mode != "predict" and m._loss is not None:
                loss = m._loss(*outs, *lb_vars)
                fetch = [loss] + fetch
            if mode == "train":
                m._optimizer.minimize(loss)
        exe = self._exe = self._exe or static.Executor()
        exe.run(startup)
        self._progs[mode] = (main, [v.name for v in in_vars + lb_vars],
                             fetch, loss is not None)
        return self._progs[mode]

    def _run(self, mode, inputs, labels):
        if mode not in self._progs:
            self._build(mode, inputs, labels)
        prog, feed_names, fetch, has_loss = self._progs[mode]
        feed = {n: np.asarray(t.numpy())
                for n, t in zip(feed_names, inputs + labels)}
        res = self._exe.run(prog, feed=feed, fetch_list=fetch)
        loss = res[0] if has_loss else None
        outs = res[1:] if has_loss else res
        return loss, [Tensor(o) for o in outs]

    def train_batch(self, inputs, labels):
        m = self.model
        loss, outs = self._run("train", inputs, labels)
        metrics = [mt.update(*_to_list(mt.compute(*outs, *labels)))
                   for mt in m._metrics]
        return m._pack(Tensor(loss), metrics)

    def eval_batch(self, inputs, labels):
        m = self.model
        loss, outs = self._run("eval", inputs, labels)
        metrics = [mt.update(*_to_list(mt.compute(*outs, *labels)))
                   for mt in m._metrics]
        return m._pack(Tensor(loss) if loss is not None else None, metrics)

    def predict_batch(self, inputs):
        _, outs = self._run("predict", inputs, [])
        return [o.numpy() for o in outs]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_configs = None
        self._train_step = None
        self._jit_compile = True
        self._accumulating = False
        self._adapter = None
        self._nan_guard = None
        self._rollback_target = None
        self._hang_detector = None
        self.stop_training = False

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None,
                jit_compile=True):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable (a Layer or function)")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle.metric.Metric, got {type(m)}")
        self._amp_configs = amp_configs
        self._jit_compile = jit_compile and amp_configs is None
        self._train_step = None
        from .. import in_dynamic_mode

        # static mode: the Program+Executor adapter (reference
        # StaticGraphAdapter); dygraph: the fused TrainStep path below
        self._adapter = None if in_dynamic_mode() else \
            StaticGraphAdapter(self)
        self._prepare_distributed_context()
        return self

    def _prepare_distributed_context(self):
        """When the user opted into fleet (fleet.init ran), place the
        network's parameters onto the mesh so TrainStep shards params per
        their dist_spec and batches over the 'data' axis (reference:
        hapi/model.py prepare_distributed_context → init_parallel_env +
        DataParallel; under GSPMD, placement IS the context). Gated on
        fleet initialization — an ambient mesh left by unrelated code must
        not reshard a model that never asked."""
        from ..distributed.fleet import _fleet_state, _place_params_on_mesh

        if not _fleet_state["initialized"]:
            return
        _place_params_on_mesh(self.network)

    def _loss_fn(self, *outs_and_labels):
        return self._loss(*outs_and_labels)

    def _traced_grad_comm_config(self):
        """The strategy's grad_comm config for the COMPILED step (ISSUE 8):
        when fleet ran with strategy.grad_comm on and the network is not an
        eager wrapper that owns its own sync (DataParallel/Sharding), the
        fused TrainStep expresses the quantized all-reduce in-trace.
        Returns None (inert) otherwise — including when no >1-replica mesh
        is active, which TrainStep itself checks."""
        from ..distributed.fleet import _fleet_state

        st = _fleet_state.get("strategy")
        if not _fleet_state.get("initialized") or st is None \
                or not getattr(st, "grad_comm", False):
            return None
        if getattr(self.network, "_grad_comm", None) is not None:
            return None   # eager wrapper syncs for itself
        from ..distributed.grad_comm import config_from_strategy

        return config_from_strategy(st)

    # -------------------------------------------------------------- batches
    def _beat(self):
        """Heartbeat the attached HangDetector — one beat per completed
        train step, so a step wedged in a collective goes stale."""
        if self._hang_detector is not None:
            self._hang_detector.beat()

    def train_batch(self, inputs, labels=None, update=True):
        inputs = _tensorize(inputs)
        labels = _tensorize(labels)
        if self._adapter is not None:
            res = self._adapter.train_batch(inputs, labels)
            self._beat()
            return res
        from ..profiler import RecordEvent

        self.network.train()
        if self._jit_compile and update and not self._accumulating \
                and self._nan_guard is None:
            if self._train_step is None:
                self._train_step = TrainStep(
                    self.network, self._loss_fn, self._optimizer,
                    grad_comm=self._traced_grad_comm_config())
            # one fused XLA program: fwd+bwd+opt are inseparable, so the
            # span is its own name rather than a fake phase split
            with RecordEvent("train_step"):
                loss = self._train_step(tuple(inputs), tuple(labels))
            # metrics reuse the step's own outputs — no extra forward
            outs = _to_list(self._train_step.last_outputs)
            metrics = []
            for m in self._metrics:
                metrics.append(m.update(*_to_list(m.compute(*outs, *labels))))
            self._beat()
            return self._pack(loss, metrics)
        # eager path (supports AMP configs / grad accumulation)
        amp_ctx = (
            amp_mod.auto_cast(**self._amp_configs)
            if isinstance(self._amp_configs, dict)
            else _nullctx()
        )
        with amp_ctx:
            with RecordEvent("forward"):
                outputs = self.network(*inputs)
                losses = self._loss(*_to_list(outputs), *labels)
        if not update:
            # accumulation micro-batch: grads must pile up RAW — disarm any
            # overlapped grad sync the wrapper's forward armed, or buckets
            # would average partial gradients mid-accumulation
            comm = getattr(self.network, "_grad_comm", None)
            if comm is not None and hasattr(comm, "abandon"):
                comm.abandon()
        with RecordEvent("backward"):
            losses.backward()
        # eager DP/sharded wrapper (DataParallel / ShardingParallel): sync
        # the gradients before the guard + optimizer see them. In overlapped
        # mode (grad_comm_configs["overlap"]) the buckets already launched
        # during backward and this is the flush barrier; serial mode runs
        # the whole bucketed sync here. Either way the sync emits the
        # step-time breakdown's "comm" span.
        if update:
            sync_fn = getattr(self.network, "apply_collective_grads", None)
            if sync_fn is not None:
                from ..distributed.env import get_world_size

                if get_world_size() > 1:
                    sync_fn()
        if update:
            action = "ok"
            if self._nan_guard is not None:
                grads = [p.grad for p in self._optimizer._parameter_list
                         if p.grad is not None]
                # may raise NanLossError / CircuitBreakerTripped per policy
                action = self._nan_guard.check(loss=losses, grads=grads)
            if action == "ok":
                with RecordEvent("optimizer"):
                    self._optimizer.step()
                    self._optimizer.clear_grad()
            else:
                # bad step: drop the poisoned gradients instead of applying
                self._optimizer.clear_grad()
                if action == "rollback":
                    tgt = self._rollback_target
                    if tgt is None or not tgt.rollback():
                        import logging

                        logging.getLogger(__name__).warning(
                            "nan_guard rollback: no RobustCheckpoint with a "
                            "valid checkpoint among callbacks — step skipped "
                            "instead")
        metrics = self._update_metrics(inputs, labels, _to_list(outputs))
        self._beat()
        return self._pack(losses, metrics)

    @autograd.no_grad()
    def _update_metrics(self, inputs, labels, outputs=None):
        if not self._metrics:
            return []
        if outputs is None:
            self.network.eval()
            outputs = _to_list(self.network(*inputs))
            self.network.train()
        res = []
        for m in self._metrics:
            res.append(m.update(*_to_list(m.compute(*outputs, *labels))))
        return res

    @autograd.no_grad()
    def eval_batch(self, inputs, labels=None):
        inputs = _tensorize(inputs)
        labels = _tensorize(labels)
        if self._adapter is not None:
            return self._adapter.eval_batch(inputs, labels)
        self.network.eval()
        outputs = _to_list(self.network(*inputs))
        metrics = []
        loss = None
        if self._loss is not None:
            loss = self._loss(*outputs, *labels)
        for m in self._metrics:
            metrics.append(m.update(*_to_list(m.compute(*outputs, *labels))))
        return self._pack(loss, metrics)

    @autograd.no_grad()
    def predict_batch(self, inputs):
        inputs = _tensorize(inputs)
        if self._adapter is not None:
            return self._adapter.predict_batch(inputs)
        self.network.eval()
        out = self.network(*inputs)
        return [o.numpy() for o in _to_list(out)]

    def _pack(self, loss, metrics):
        loss_np = [float(loss.numpy())] if loss is not None else []
        if self._metrics:
            return loss_np, metrics
        return loss_np

    # ------------------------------------------------------------------ fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False,
            shuffle=True, num_workers=0, callbacks=None, accumulate_grad_batches=1,
            num_iters=None, nan_guard=None, hang_detector=None, telemetry=None,
            preemption=None):
        train_loader = self._make_loader(train_data, batch_size, shuffle, drop_last,
                                         num_workers)
        eval_loader = (
            self._make_loader(eval_data, batch_size, False, False, num_workers)
            if eval_data is not None else None
        )
        steps = self._try_len(train_loader)
        # distributed telemetry (ISSUE 6): `telemetry=` attaches a
        # MetricsCallback with periodic CROSS-RANK aggregation — every N
        # steps each rank's registry snapshot is merged on rank 0 and the
        # per-rank step-time spread lands on the step_time_skew straggler
        # gauge. True = every 10 steps; an int = that period; a
        # MetricsAggregator = aggregate through it (tests inject emulated
        # multi-rank gathers this way). The exposition endpoint starts too
        # when FLAGS_telemetry_http_port is set.
        callbacks = list(callbacks or [])
        if telemetry is None:
            # fleet-opted jobs inherit the strategy's telemetry knobs
            from ..distributed.fleet import _fleet_state

            st = _fleet_state.get("strategy")
            if st is not None and getattr(st, "telemetry", False):
                n = int(st.telemetry_configs.get(
                    "aggregate_every_n_steps", 0) or 0)
                telemetry = n if n > 1 else True
        if telemetry:
            from .callbacks import MetricsCallback

            if not any(isinstance(c, MetricsCallback) for c in callbacks):
                from ..observability import MetricsAggregator

                freq = telemetry if isinstance(telemetry, int) and \
                    not isinstance(telemetry, bool) and telemetry > 1 else 10
                agg = (telemetry if isinstance(telemetry, MetricsAggregator)
                       else None)
                callbacks.append(MetricsCallback(freq=freq, aggregate=True,
                                                 aggregator=agg))
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps, log_freq=log_freq,
            verbose=verbose, save_freq=save_freq, save_dir=save_dir,
            metrics=self._metric_names(),
        )
        self.stop_training = False
        # grad accumulation needs the eager tape (grads build up in p.grad
        # across micro-batches); the fused jit step computes fresh grads
        self._accumulating = accumulate_grad_batches > 1
        # NaN guarding also runs eager: skipping/rolling back an update needs
        # the step decision BEFORE optimizer.step(), which the fused jitted
        # TrainStep has already folded in
        self._nan_guard = None
        self._rollback_target = None
        if nan_guard is not None:
            from ..robustness.watchdog import NanGuard

            self._nan_guard = nan_guard if isinstance(nan_guard, NanGuard) \
                else NanGuard(policy=str(nan_guard))
            from .callbacks import RobustCheckpoint

            self._rollback_target = next(
                (c for c in cbks.callbacks if isinstance(c, RobustCheckpoint)),
                None)
        # preemption tolerance (ISSUE 10): `preemption=` attaches a
        # robustness.PreemptionHandler — a PreemptionHandler instance, or
        # True for a default SIGTERM latch installed for this fit. The
        # step loop checks it at STEP boundaries (the one consistent
        # point); a hit fires an emergency checkpoint through the
        # RobustCheckpoint callback (tagged reason="preemption", exempt
        # from retention GC), sets `self.preempted`, and stops training
        # with a resumable status available from the handler.
        self.preempted = False
        ph = None
        ph_installed = False
        if preemption is not None and preemption is not False:
            from ..robustness.preemption import PreemptionHandler

            ph = (preemption if isinstance(preemption, PreemptionHandler)
                  else PreemptionHandler())
            if not ph.installed:
                ph.install()
                ph_installed = True
        # hang detection: one beat per train step (train_batch._beat); the
        # detector is also registered as the collective-timeout escalation
        # target (robustness/distributed_ft) for the duration of the fit
        hd_started = False
        prev_hd = None
        if hang_detector is not None:
            from ..robustness import distributed_ft as _dft
            from ..robustness.watchdog import HangDetector

            hd = hang_detector if isinstance(hang_detector, HangDetector) \
                else HangDetector(timeout=float(hang_detector))
            self._hang_detector = hd
            prev_hd = _dft.set_default_hang_detector(hd)
            if hd._thread is None:
                hd.start()
                hd_started = True
        try:
            cbks.on_train_begin()
            step_count = 0
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                accum = 0
                # manual iteration so the batch FETCH is a "data" span — the
                # step-time breakdown's data phase (loader stalls show up
                # here)
                from ..profiler import RecordEvent

                loader_iter = iter(train_loader)
                step = -1
                while True:
                    with RecordEvent("data"):
                        batch = next(loader_iter, _STOP)
                    if batch is _STOP:
                        break
                    step += 1
                    cbks.on_train_batch_begin(step)
                    ins, lbls = self._split_batch(batch)
                    accum += 1
                    update = accum % accumulate_grad_batches == 0
                    res = self.train_batch(ins, lbls, update=update)
                    logs = self._logs_from(res)
                    cbks.on_train_batch_end(step, logs)
                    step_count += 1
                    if ph is not None and ph.should_stop():
                        # step boundary: model/optimizer/job state are
                        # consistent — commit the emergency checkpoint and
                        # exit the fit resumably
                        self.preempted = True
                        self.stop_training = True
                        self._emergency_checkpoint(cbks, step_count)
                        break
                    if num_iters is not None and step_count >= num_iters:
                        self.stop_training = True
                        break
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self._run_eval(eval_loader, cbks)
                if self.stop_training:
                    break
            cbks.on_train_end()
        finally:
            if ph_installed:
                ph.uninstall()
            if hang_detector is not None:
                _dft.set_default_hang_detector(prev_hd)
                if hd_started:
                    hd.stop()
                self._hang_detector = None

    def _emergency_checkpoint(self, cbks, step_count):
        """Preemption hit: commit an emergency save through the
        RobustCheckpoint callback when one is attached (the normal
        production wiring); without one the stop is still clean — the
        newest periodic checkpoint is the resume point."""
        from .callbacks import RobustCheckpoint

        rc = next((c for c in cbks.callbacks
                   if isinstance(c, RobustCheckpoint)), None)
        if rc is None:
            import logging

            logging.getLogger(__name__).warning(
                "preemption latched but no RobustCheckpoint callback is "
                "attached — stopping without an emergency save (resume "
                "falls back to the newest periodic checkpoint)")
            return None
        return rc.emergency_save(step_count)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_samples=None):
        loader = self._make_loader(eval_data, batch_size, False, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                metrics=self._metric_names())
        return self._run_eval(loader, cbks)

    def _run_eval(self, loader, cbks):
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = self._split_batch(batch)
            res = self.eval_batch(ins, lbls)
            logs = self._logs_from(res)
            cbks.on_eval_batch_end(step, logs)
        final = {}
        if self._loss is not None and "loss" in logs:
            final["loss"] = logs["loss"]
        for m in self._metrics:
            final[_name_str(m)] = m.accumulate()
        cbks.on_eval_end(final)
        return final

    @autograd.no_grad()
    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        # transpose list-of-batches into per-output lists
        n_out = len(outputs[0]) if outputs else 0
        res = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            res = [np.concatenate(r, axis=0) for r in res]
        return res

    # ------------------------------------------------------------- helpers
    def _make_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _try_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _split_batch(self, batch, has_labels=True):
        batch = _to_list(batch)
        if not has_labels:
            # predict: honor the inputs spec; else assume a (x, [label...]) tuple
            # feeds the model only x (labels are simply dropped)
            n_in = len(_to_list(self._inputs)) if self._inputs else (
                1 if len(batch) > 1 else len(batch)
            )
            return batch[:n_in], []
        if len(batch) == 1:
            return batch, []
        n_lbl = len(_to_list(self._labels)) if self._labels else 1
        return batch[:-n_lbl], batch[-n_lbl:]

    def _logs_from(self, res):
        logs = {}
        if self._metrics:
            loss_np, metrics = res
        else:
            loss_np, metrics = res, []
        if loss_np:
            logs["loss"] = loss_np[0] if len(loss_np) == 1 else loss_np
        for m, v in zip(self._metrics, metrics):
            logs[_name_str(m)] = v
        return logs

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    # --------------------------------------------------------------- state
    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def save(self, path, training=True):
        from ..framework.io import save as psave

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload

        self.network.set_state_dict(pload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and os.path.exists(
            path + ".pdopt"
        ):
            self._optimizer.set_state_dict(pload(path + ".pdopt"))
        # set_state_dict rebinds values without shardings — re-place so a
        # fleet-prepared model stays sharded after a checkpoint load
        self._prepare_distributed_context()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)


_STOP = object()  # loader-exhausted sentinel for the fit data-span loop


def _name_str(m):
    n = m.name()
    return n if isinstance(n, str) else n[0]


class _nullctx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

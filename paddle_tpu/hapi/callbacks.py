"""hapi callbacks (reference: python/paddle/hapi/callbacks.py:297-958)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Console progress logger (callbacks.py:297)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.step = 0
        self._start = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple, np.ndarray)):
                v = ", ".join(f"{float(x):.4f}" for x in np.atleast_1d(v))
                parts.append(f"{k}: [{v}]")
            elif isinstance(v, numbers.Number):
                parts.append(f"{k}: {float(v):.4f}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        self.step = step + 1
        if self.verbose == 2 and self.step % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {self.step}{total} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Save every N epochs (callbacks.py:533)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class RobustCheckpoint(Callback):
    """ModelCheckpoint with crash-safe semantics: atomic manifest-committed
    `step_NNNNNN/` checkpoints (robustness/checkpoint.py) holding model AND
    optimizer state, keep-last-N retention, optional async commit. Also the
    rollback target for NanGuardCallback / Model.fit(nan_guard=...)."""

    def __init__(self, save_dir, save_freq=1, keep_last_n=3,
                 async_save=False, job_state_fn=None):
        super().__init__()
        self.save_dir = save_dir
        self.save_freq = save_freq
        self.keep_last_n = keep_last_n
        self.async_save = async_save
        # job_state_fn() -> dict: resume-critical runtime state captured
        # alongside the weights (distributed_ft.capture_job_state shape).
        # Default captures the RNG streams + the fit-installed NanGuard, so
        # even a plain RobustCheckpoint(save_dir) resume is deterministic.
        self.job_state_fn = job_state_fn
        self.manager = None
        self.last_saved_epoch = None

    def _ensure_manager(self):
        if self.manager is None:
            from ..robustness.checkpoint import CheckpointManager

            self.manager = CheckpointManager(self.save_dir,
                                             keep_last_n=self.keep_last_n)
        return self.manager

    def _payload(self):
        payload = {"model": self.model.network.state_dict()}
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and hasattr(opt, "state_dict"):
            payload["optimizer"] = opt.state_dict()
        return payload

    def _job_state(self):
        if self.job_state_fn is not None:
            return self.job_state_fn()
        from ..robustness.distributed_ft import capture_job_state

        return capture_job_state(
            nan_guard=getattr(self.model, "_nan_guard", None))

    def _save(self, epoch):
        mgr = self._ensure_manager()
        save = mgr.save_async if self.async_save else mgr.save
        save(self._payload(), epoch, job_state=self._job_state())
        self.last_saved_epoch = epoch

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self._save(epoch)

    def on_train_end(self, logs=None):
        if self.manager is not None:
            self.manager.close()

    def emergency_save(self, step, reason="preemption"):
        """Commit one emergency checkpoint NOW (preemption path): async
        manifest-committed save of model+optimizer+job_state tagged
        ``metadata.reason`` (retention GC exempts 'preemption'), waited to
        completion so it lands inside the grace window. Returns the
        elapsed wall ms."""
        from ..robustness.preemption import timed_emergency_save

        mgr = self._ensure_manager()
        return timed_emergency_save(
            mgr, self._payload(), step, job_state=self._job_state(),
            metadata={"reason": reason})

    def rollback(self):
        """Restore the newest valid checkpoint into the live model/optimizer.
        Returns False when nothing valid exists to roll back to."""
        mgr = self._ensure_manager()
        mgr.wait()
        found = mgr.load_latest()
        if found is None:
            return False
        payload, step, _ = found
        self.model.network.set_state_dict(payload["model"])
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and "optimizer" in payload and \
                hasattr(opt, "set_state_dict"):
            opt.set_state_dict(payload["optimizer"])
        return True

    def resume(self, reducer=None, data_iter=None, nan_guard=None):
        """Deterministic full-job resume: restore model + optimizer from
        the newest valid checkpoint AND its job_state (RNG streams, data
        position, grad_comm residuals, breaker counters) into the live
        objects. Returns the resumed step, or None when nothing valid
        exists (cold start)."""
        from ..robustness.distributed_ft import restore_job_state

        mgr = self._ensure_manager()
        mgr.wait()
        found = mgr.load_latest()
        if found is None:
            return None
        payload, step, _ = found
        self.model.network.set_state_dict(payload["model"])
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and "optimizer" in payload and \
                hasattr(opt, "set_state_dict"):
            opt.set_state_dict(payload["optimizer"])
        job_state = mgr.load_job_state(step)
        if job_state:
            restore_job_state(job_state, reducer=reducer,
                              data_iter=data_iter, nan_guard=nan_guard)
        return step


class NanGuardCallback(Callback):
    """Watches the monitored log value (default "loss") for NaN/Inf each
    batch through robustness.NanGuard: policy "skip_step" just records,
    "rollback" restores the paired RobustCheckpoint, "raise" aborts fit; a
    consecutive-bad-step circuit breaker overrides any policy. A step the
    given GradScaler skipped (fp16 overflow) is exempt."""

    def __init__(self, policy="skip_step", max_consecutive_bad=8,
                 checkpoint=None, scaler=None, monitor="loss"):
        super().__init__()
        from ..robustness.watchdog import NanGuard

        self.guard = NanGuard(policy=policy,
                              max_consecutive_bad=max_consecutive_bad)
        self.checkpoint = checkpoint
        self.scaler = scaler
        self.monitor = monitor

    def on_train_batch_end(self, step, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        if isinstance(val, (list, tuple)):
            val = val[0] if val else None
        skipped = bool(getattr(self.scaler, "last_step_skipped", False))
        action = self.guard.check(loss=val, scaler_skipped=skipped)
        if action == "rollback":
            if self.checkpoint is None or not self.checkpoint.rollback():
                import logging

                logging.getLogger(__name__).warning(
                    "NanGuardCallback: rollback requested but no valid "
                    "RobustCheckpoint available — continuing without restore")


class MetricsCallback(Callback):
    """Telemetry dumper (ISSUE 3 + 6): every `freq` train steps (and at
    train end) appends one JSONL record holding the process-global
    MetricsRegistry snapshot plus the per-step time breakdown since the
    last dump (data / forward / backward / optimizer / comm / checkpoint,
    assembled by an observability.StepTimer from the RecordEvent spans
    Model.train_batch / Model.fit emit).

        model.fit(data, callbacks=[MetricsCallback(log_dir="tele", freq=20)])

    Distributed plane (ISSUE 6): every step's wall time feeds the rank's
    step-time window (aggregate.note_step_time); with `aggregate=True` (or
    an explicit MetricsAggregator) each dump also runs one cross-rank
    aggregation round — rank 0's merged view plus the `step_time_skew`
    straggler gauge land in the record under "aggregated". Each dump also
    takes a memory-accounting sample (live-tensor bytes + allocator
    peak gauges), and on_train_begin starts the exposition endpoint when
    FLAGS_telemetry_http_port is set.

    Records land in `<log_dir>/metrics.jsonl`; without a log_dir they are
    kept on `.snapshots` (bounded by dumps, not steps). `last_snapshot`
    always holds the newest record for in-process consumers.
    """

    def __init__(self, log_dir=None, freq=10, registry=None, aggregate=False,
                 aggregator=None):
        super().__init__()
        from ..observability import MetricsAggregator, StepTimer, get_registry

        self.log_dir = log_dir
        self.freq = int(freq)
        self.registry = registry or get_registry()
        self.timer = StepTimer(registry=self.registry)
        self.aggregator = aggregator or (
            MetricsAggregator(registry=self.registry) if aggregate else None)
        self.snapshots = []
        self._global_step = 0
        self._last_dump_idx = 0

    @property
    def last_snapshot(self):
        return self.snapshots[-1] if self.snapshots else None

    def on_train_begin(self, logs=None):
        from ..observability import start_exposition

        self._global_step = 0
        self._last_dump_idx = 0
        self.timer.start()
        # no-op unless FLAGS_telemetry_http_port is set; idempotent
        start_exposition(aggregator=self.aggregator)

    def on_train_batch_end(self, step, logs=None):
        from ..observability import note_step_time

        row = self.timer.step()
        note_step_time(row.get("total", 0.0))
        self._global_step += 1
        if self.freq and self._global_step % self.freq == 0:
            self._dump(logs)

    def on_train_end(self, logs=None):
        if len(self.timer.steps) > self._last_dump_idx or not self.snapshots:
            self._dump(logs)
        self.timer.stop()

    def _dump(self, logs=None):
        import json

        from ..observability import memory as obs_memory
        from ..observability.step_timer import aggregate_rows

        rows = self.timer.steps[self._last_dump_idx:]
        self._last_dump_idx = len(self.timer.steps)
        rec = {
            "time": time.time(),
            "step": self._global_step,
            "metrics": self.registry.snapshot(),
            "step_breakdown": aggregate_rows(rows),
            "memory": obs_memory.sample(),
        }
        if self.aggregator is not None:
            agg = self.aggregator.aggregate()
            rec["aggregated"] = {
                "ranks": agg["ranks"],
                "step_time_skew": agg["step_time_skew"],
                "step_time": agg["step_time"],
                "degraded": agg.get("degraded"),
            }
        loss = (logs or {}).get("loss")
        if isinstance(loss, numbers.Number):
            rec["loss"] = float(loss)
        self.snapshots.append(rec)
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            with open(os.path.join(self.log_dir, "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        return rec


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (callbacks.py:LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """(callbacks.py:EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            -np.inf if self.mode == "max" else np.inf
        )
        self.model.stop_training = False

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.atleast_1d(cur)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"], "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor} = {self.best:.5f}")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1, mode="auto",
                 min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.cooldown_counter = 0
        self.best = -np.inf if self.mode == "max" else np.inf

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.atleast_1d(cur)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        better = cur > self.best + self.min_delta if self.mode == "max" else (
            cur < self.best - self.min_delta
        )
        if better:
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None and not callable(getattr(opt._learning_rate, "step", None)):
                    new_lr = max(opt.get_lr() * self.factor, self.min_lr)
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {new_lr:.2e}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging callback. VisualDL itself isn't bundled; falls back to a
    jsonl scalars file readable by TensorBoard text or custom tooling."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def _write(self, tag, value, step):
        import json

        if self._f is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")
        self._f.write(json.dumps({"tag": tag, "value": float(value), "step": step}) + "\n")
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self._write(f"train/{k}", v, self._step)

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self._write(f"eval/{k}", v, self._step)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, (ModelCheckpoint, RobustCheckpoint))
               for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    params = {
        "batch_size": batch_size,
        "epochs": epochs,
        "steps": steps,
        "verbose": verbose,
        "metrics": metrics or [],
        "save_dir": save_dir,
    }
    cbk_list.set_params(params)
    return cbk_list

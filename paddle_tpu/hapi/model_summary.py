"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params': N, 'trainable_params': N}."""
    rows = []
    hooks = []
    layer_count = [0]

    def register(layer):
        def hook(l, inputs, outputs):
            layer_count[0] += 1
            n_params = sum(
                int(np.prod(p._value.shape)) for p in l._parameters.values() if p is not None
            )
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            rows.append((f"{type(l).__name__}-{layer_count[0]}", str(shape), n_params))

        if not l_has_children(layer):
            hooks.append(layer.register_forward_post_hook(hook))

    def l_has_children(l):
        return len(l._sub_layers) > 0

    for l in net.sublayers(include_self=True):
        register(l)

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
        net(*x)
    elif input_size is not None:
        sizes = input_size if isinstance(input_size, list) else [input_size]
        xs = []
        for i, s in enumerate(sizes):
            dt = (dtypes[i] if isinstance(dtypes, (list, tuple)) else dtypes) or "float32"
            shape = [d if d is not None and d > 0 else 1 for d in s]
            xs.append(Tensor(np.zeros(shape, dtype="float32"), dtype=dt))
        was_training = net.training
        net.eval()
        net(*xs)
        if was_training:
            net.train()
    for h in hooks:
        h.remove()

    total = sum(int(np.prod(p._value.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p._value.shape)) for p in net.parameters() if not p.stop_gradient
    )
    width = 64
    print("-" * width)
    print(f"{'Layer (type)':<28}{'Output Shape':<22}{'Param #':>12}")
    print("=" * width)
    for name, shape, n in rows:
        print(f"{name:<28}{shape:<22}{n:>12,}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs counter (reference: hapi/dynamic_flops.py). Counts the dominant
    matmul/conv contributions via forward hooks."""
    total = [0]
    hooks = []

    def conv_hook(l, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        kshape = l.weight.shape  # [out_c, in_c/g, *k]
        out_spatial = int(np.prod(out.shape[2:]))
        total[0] += 2 * out.shape[0] * out_spatial * int(np.prod(kshape))

    def linear_hook(l, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
        total[0] += 2 * int(np.prod(out.shape[:-1])) * l.weight.shape[0] * l.weight.shape[1]

    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd

    for l in net.sublayers(include_self=True):
        if isinstance(l, _ConvNd):
            hooks.append(l.register_forward_post_hook(conv_hook))
        elif isinstance(l, Linear):
            hooks.append(l.register_forward_post_hook(linear_hook))

    shape = [d if d and d > 0 else 1 for d in input_size]
    was_training = net.training
    net.eval()
    net(Tensor(np.zeros(shape, np.float32)))
    if was_training:
        net.train()
    for h in hooks:
        h.remove()
    return total[0]

"""paddle.regularizer — L1/L2 weight decay (parity: python/paddle/
regularizer.py; applied by the optimizer update, fluid/regularizer.py)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff

    def __repr__(self):
        return f"L2Decay(coeff={self._coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff
        self._l1 = True

    def __repr__(self):
        return f"L1Decay(coeff={self._coeff})"

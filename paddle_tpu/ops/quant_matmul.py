"""Pallas int8 weight-only quantized matmul + quantize kernel.

Reference capability: the int8 kernels behind paddle's quantization
deployment (operators/fused/quant_dequant kernels, mkldnn int8 path).
TPU-native: weight-only int8 with per-output-channel scales — the memory-
bound serving case where halving weight bytes doubles effective HBM
bandwidth; the MXU consumes the dequantized tile from VMEM.

Determinism contract (ISSUE 13): ``quantize_int8`` is a pure function of
``(w, stochastic, seed)`` — the stochastic rounding derives its noise
from a counter-based integer hash of (element index, seed) computed with
plain uint32 arithmetic inside the kernel, so the SAME seed yields the
SAME int8 weights on every platform, in every process, on every call.
(The previous ``pltpu.prng_*`` path tied the bits to the backend and has
no interpret-mode lowering at all — stochastic quantization simply
crashed on CPU.)

Kernels:
  quantize_int8(w, seed=)     -> (int8 values, f32 per-col scales)
  quant_matmul(x, qw, scales) -> x @ dequant(qw)   (bf16/f32 in, f32 acc)

quant_matmul's m/n/k tiles are tuner-dispatched: family "quant_matmul"
in the autotune cache under FLAGS_kernel_autotune; explicit block_m/n/k
arguments pin them, and both fall back to the (256, 256, 512) defaults.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    from ..framework.target import target_platform

    return target_platform() != "tpu"


# ---------------------------------------------------------------------------
# quantize: per-output-channel symmetric int8
# ---------------------------------------------------------------------------

def _hash_uniform(shape, seed_u32):
    """[0, 1) uniforms from a murmur3-finalizer hash of (element index,
    seed): pure uint32 arithmetic — identical bits under Mosaic, the
    interpreter, and XLA:CPU. The per-element counter is the GLOBAL flat
    index, so any future tiling of this kernel cannot change the noise."""
    r, c = shape
    idx = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0) * jnp.uint32(c)
           + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    h = idx * jnp.uint32(2654435761) ^ seed_u32
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EB_CA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2_AE35)
    h = h ^ (h >> 16)
    return (h >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _quantize_kernel(w_ref, seed_ref, q_ref, s_ref, *, stochastic):
    w = w_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0, keepdims=True)          # per col
    scale = jnp.maximum(amax / 127.0, 1e-12)
    scaled = w / scale
    if stochastic:
        u = _hash_uniform(scaled.shape, seed_ref[0].astype(jnp.uint32))
        # floor(x + u) rounds up with probability frac(x): unbiased
        q = jnp.clip(jnp.floor(scaled + u), -127, 127).astype(jnp.int8)
    else:
        q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    q_ref[...] = q
    s_ref[...] = scale


def quantize_int8(w, stochastic=False, seed=0):
    """[k, n] float weights → ([k, n] int8, [1, n] f32 scales).

    Deterministic: same (w, stochastic, seed) → bit-identical int8 on
    every platform and process (see module docstring)."""
    k, n = w.shape
    q, s = pl.pallas_call(
        functools.partial(_quantize_kernel, stochastic=stochastic),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, n), jnp.int8),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        interpret=_interpret(),
    )(w, jnp.asarray([int(seed) & 0x7FFF_FFFF], jnp.int32))
    return q, s


def stable_seed(name: str, base: int = 0) -> int:
    """Process-stable seed for a named weight: crc32 (NOT the salted
    builtin ``hash``) so every process, rank, and run derives the same
    stochastic-rounding bits for the same parameter name."""
    import zlib

    return (int(base) + zlib.crc32(name.encode("utf-8"))) & 0x7FFF_FFFF


# ---------------------------------------------------------------------------
# quantized matmul: grid over (m, n) tiles, k streamed
# ---------------------------------------------------------------------------

def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    wq = q_ref[...].astype(jnp.float32)                        # dequant tile
    acc_ref[...] += jax.lax.dot(x, wq,
                                preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


_DEFAULT_TILES = (256, 256, 512)


def _tuned_tiles(m: int, n: int, k: int, dtype):
    """(block_m, block_n, block_k) from the tuner cache when the entry
    still tiles this concrete problem, else the defaults."""
    from .pallas import autotune as _at

    params = _at.lookup("quant_matmul", (m, k, n), jnp.dtype(dtype))
    if params:
        bm = int(params.get("block_m", 0))
        bn = int(params.get("block_n", 0))
        bk = int(params.get("block_k", 0))
        if bm > 0 and bn > 0 and bk > 0 \
                and m % min(bm, m) == 0 and n % min(bn, n) == 0 \
                and k % min(bk, k) == 0:
            return bm, bn, bk
        _at.count_dispatch("quant_matmul", "fallback")
    return _DEFAULT_TILES


def quant_matmul(x, qw, scales, block_m=None, block_n=None, block_k=None,
                 out_dtype=None):
    """x [m, k] @ dequant(qw [k, n], scales [1, n]) -> [m, n].

    Explicit block_m/n/k pin the tiles; otherwise dispatch consults the
    autotune cache under FLAGS_kernel_autotune and falls back to the
    (256, 256, 512) defaults."""
    m, k = x.shape
    k2, n = qw.shape
    assert k == k2, (x.shape, qw.shape)
    if block_m is None and block_n is None and block_k is None:
        block_m, block_n, block_k = _tuned_tiles(m, n, k, x.dtype)
    else:
        block_m = block_m or _DEFAULT_TILES[0]
        block_n = block_n or _DEFAULT_TILES[1]
        block_k = block_k or _DEFAULT_TILES[2]
    bm = min(block_m, m)
    bn = min(block_n, n)
    bk = min(block_k, k)
    if m % bm or n % bn or k % bk:
        # ragged shapes: plain XLA dequant matmul (still weight-only int8 in
        # HBM — the bandwidth saving survives; only the tiling control is lost)
        out = x.astype(jnp.float32) @ (qw.astype(jnp.float32) * scales)
        return out.astype(out_dtype or x.dtype)
    n_k = k // bk
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, n_k=n_k),
        grid=(m // bm, n // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)),
            pl.BlockSpec((1, bn), lambda i, j, ki: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype or x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_interpret(),
    )(x, qw, scales)
    return out

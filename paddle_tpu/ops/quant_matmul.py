"""Pallas int8 weight-only quantized matmul + quantize kernel.

Reference capability: the int8 kernels behind paddle's quantization
deployment (operators/fused/quant_dequant kernels, mkldnn int8 path).
TPU-native: weight-only int8 with per-output-channel scales — the memory-
bound serving case where halving weight bytes doubles effective HBM
bandwidth; the MXU consumes the dequantized tile from VMEM. The quantizer
kernel uses pltpu stochastic rounding (pallas_guide quantization pattern).

Kernels:
  quantize_int8(w)            -> (int8 values, f32 per-col scales)
  quant_matmul(x, qw, scales) -> x @ dequant(qw)   (bf16/f32 in, f32 acc)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    from ..framework.target import target_platform

    return target_platform() != "tpu"


# ---------------------------------------------------------------------------
# quantize: per-output-channel symmetric int8
# ---------------------------------------------------------------------------

def _quantize_kernel(w_ref, seed_ref, q_ref, s_ref, *, stochastic):
    w = w_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0, keepdims=True)          # per col
    scale = jnp.maximum(amax / 127.0, 1e-12)
    scaled = w / scale
    if stochastic:
        pltpu.prng_seed(seed_ref[0])
        bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape),
                             jnp.uint32)
        q = pltpu.stochastic_round(scaled, bits, target_dtype=jnp.int8)
    else:
        q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    q_ref[...] = q
    s_ref[...] = scale


def quantize_int8(w, stochastic=False, seed=0):
    """[k, n] float weights → ([k, n] int8, [1, n] f32 scales)."""
    k, n = w.shape
    q, s = pl.pallas_call(
        functools.partial(_quantize_kernel, stochastic=stochastic),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, n), jnp.int8),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        interpret=_interpret(),
    )(w, jnp.asarray([seed], jnp.int32))
    return q, s


# ---------------------------------------------------------------------------
# quantized matmul: grid over (m, n) tiles, k streamed
# ---------------------------------------------------------------------------

def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    wq = q_ref[...].astype(jnp.float32)                        # dequant tile
    acc_ref[...] += jax.lax.dot(x, wq,
                                preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def quant_matmul(x, qw, scales, block_m=256, block_n=256, block_k=512,
                 out_dtype=None):
    """x [m, k] @ dequant(qw [k, n], scales [1, n]) -> [m, n]."""
    m, k = x.shape
    k2, n = qw.shape
    assert k == k2, (x.shape, qw.shape)
    bm = min(block_m, m)
    bn = min(block_n, n)
    bk = min(block_k, k)
    if m % bm or n % bn or k % bk:
        # ragged shapes: plain XLA dequant matmul (still weight-only int8 in
        # HBM — the bandwidth saving survives; only the tiling control is lost)
        out = x.astype(jnp.float32) @ (qw.astype(jnp.float32) * scales)
        return out.astype(out_dtype or x.dtype)
    n_k = k // bk
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, n_k=n_k),
        grid=(m // bm, n // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)),
            pl.BlockSpec((1, bn), lambda i, j, ki: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype or x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_interpret(),
    )(x, qw, scales)
    return out

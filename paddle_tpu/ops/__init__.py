"""paddle_tpu.ops — Pallas TPU kernels for the hot ops.

The reference implements its fused hot ops as CUDA kernels
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h); here they
are Pallas TPU kernels driving the MXU directly, with fp32 accumulators and
online-softmax streaming so the score matrix never materializes in HBM.
"""
from .quant_matmul import quant_matmul, quantize_int8  # noqa: F401
from .flash_attention import (  # noqa: F401
    flash_attention_val, flash_attention_supported,
)

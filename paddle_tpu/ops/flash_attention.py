"""Flash attention as a Pallas TPU kernel (forward + backward).

Capability parity: the reference's fused CUDA attention
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h) — here
re-designed for the TPU memory hierarchy: the kv dimension is the innermost
grid axis, so k/v blocks stream HBM→VMEM with automatic double-buffering,
online-softmax state lives in VMEM scratch across grid steps, the [s, s]
score matrix never exists in HBM, and the MXU does every matmul with fp32
accumulation (preferred_element_type=f32). Causal upper-triangle blocks are
predicated off with @pl.when, realizing the ~2x causal FLOP saving.

Layout is [b, n, s, d] inside the kernels (head-major, contiguous (s, d)
tiles per grid cell); the public entry takes the model's [b, s, n, d] and
transposes (XLA fuses the transposes into the surrounding program).

Backward uses the standard two-kernel flash decomposition:
  dq kernel:  grid (b, n, q_blocks, kv_blocks), dq accumulates in scratch
  dkv kernel: grid (b, n, kv_blocks, q_blocks), dk/dv accumulate in scratch
with delta = rowsum(dO * O) precomputed outside (one fused elementwise pass).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(-1e30)
_LANES = 128  # m/l scratch lane width (min f32 tile is (8, 128))


def _pick_block(s: int, want: int) -> int:
    """The pre-tuner preference ladder: largest power-of-two block <= want
    that divides s. The FALLBACK when the tune cache has no validated
    winner for the shape (and the whole story when FLAGS_kernel_autotune
    is off)."""
    for b in (want, 512, 256, 128, 64, 32, 16, 8):
        if b <= want and s % b == 0 and b <= s:
            return b
    return 0


def _tuned_blocks(shape, dtype, causal: bool, want: int):
    """(block_q, block_k) for a [b, s, n, d] call: the tuner cache's
    validated winner under FLAGS_kernel_autotune when it still fits the
    concrete sequence length, else the _pick_block ladder pair. The
    independent q/k blocks are the point — the cache may hold an
    asymmetric winner the ladder can never produce."""
    s = int(shape[1])
    from .pallas import autotune as _at

    params = _at.lookup(
        "flash_attention", tuple(int(x) for x in shape),
        f"{jnp.dtype(dtype)}-{'causal' if causal else 'full'}")
    if params:
        bq = int(params.get("block_q", 0))
        bk = int(params.get("block_k", 0))
        if bq >= 8 and bk >= 8 and s % bq == 0 and s % bk == 0:
            return bq, bk, "tuned"
        # tuned entry no longer fits this concrete shape (bucket
        # collision): fall back loudly in the dispatch counter
        _at.count_dispatch("flash_attention", "fallback")
        blk = _pick_block(s, want)
        return blk, blk, "fallback"
    blk = _pick_block(s, want)
    return blk, blk, "default"


def flash_block_choice(shape, dtype="float32", causal=True,
                       block_size=512) -> dict:
    """What dispatch would run for this [b, s, n, d] call — the record
    bench.py carries so the trajectory shows WHICH tiles produced a
    throughput number: {"block_q", "block_k", "source"}."""
    bq, bk, source = _tuned_blocks(tuple(shape), dtype, bool(causal),
                                   block_size)
    return {"block_q": int(bq), "block_k": int(bk), "source": source}


def flash_attention_supported(q_shape, block: int = 512,
                              block_q: int = None,
                              block_k: int = None) -> bool:
    """True if the kernel can handle this [b, s, n, d] shape. With
    explicit ``block_q``/``block_k`` the check honors the independent
    tiles (s must divide by BOTH); with neither, the ladder must find a
    block <= ``block``."""
    if len(q_shape) != 4:
        return False
    s = int(q_shape[1])
    if block_q is not None or block_k is not None:
        bq = int(block_q or block)
        bk = int(block_k or block)
        return (bq >= 8 and bk >= 8 and bq <= s and bk <= s
                and s % bq == 0 and s % bk == 0)
    return _pick_block(s, block) >= 8


def _interpret() -> bool:
    from ..framework.target import target_platform

    return target_platform() != "tpu"


def _causal_mask(s_blk, qi, ki, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s_blk, NEG_INF)


# ---------------------------------------------------------------------------
# forward — grid (b, n, q_blocks, kv_blocks), kv innermost
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: the block computes only if some q_pos >= some k_pos
    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale        # (BQ, d)
        kb = k_ref[0, 0, :, :].astype(jnp.float32)               # (BK, d)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        s_blk = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (BQ, BK)
        if causal:
            s_blk = _causal_mask(s_blk, qi, ki, block_q, block_k)
        m_prev = m_ref[:, :1]                                    # (BQ, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, -1, keepdims=True))
        p = jnp.exp(s_blk - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (BQ, d)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0, :, :] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_ref[:, :1] + jnp.log(l)


def _sds(shape, dtype, like):
    """Out ShapeDtypeStruct carrying `like`'s varying-mesh-axes set, so the
    pallas_call stays legal inside vma-tracked shard_map regions (the 1F1B
    pipeline, ring attention's manual block)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)
    if not vma:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _fwd(q, k, v, causal, block_q, block_k):
    b, n, s, d = q.shape
    grid = (b, n, s // block_q, s // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=1.0 / math.sqrt(d),
                          causal=causal, block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            _sds((b, n, s, d), q.dtype, q),
            _sds((b, n, s, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_k):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale        # (BQ, d)
        kb = k_ref[0, 0, :, :].astype(jnp.float32)               # (BK, d)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]                                # (BQ, 1)
        delta = delta_ref[0, 0, :, :]
        s_blk = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s_blk = _causal_mask(s_blk, qi, ki, block_q, block_k)
        p = jnp.exp(s_blk - lse)                                 # (BQ, BK)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0, :, :] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k):
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale        # (BQ, d)
        kb = k_ref[0, 0, :, :].astype(jnp.float32)               # (BK, d)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s_blk = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (BQ, BK)
        if causal:
            s_blk = _causal_mask(s_blk, qi, ki, block_q, block_k)
        p = jnp.exp(s_blk - lse)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (BK, d)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # q was pre-scaled, so dk already carries `scale`
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (BK, d)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0, :, :] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, block_q, block_k):
    b, n, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                      # (b, n, s, 1)
    qb = pl.BlockSpec((1, 1, block_q, d),
                      lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kvb = pl.BlockSpec((1, 1, block_k, d),
                       lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    rowb = pl.BlockSpec((1, 1, block_q, 1),
                        lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=1.0 / math.sqrt(d), causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, n, s // block_q, s // block_k),
        in_specs=[qb, kvb, kvb, qb, rowb, rowb],
        out_specs=qb,
        out_shape=_sds((b, n, s, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dkv: grid (b, n, kv_blocks, q_blocks) — q innermost
    qb2 = pl.BlockSpec((1, 1, block_q, d),
                       lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kvb2 = pl.BlockSpec((1, 1, block_k, d),
                        lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    rowb2 = pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=1.0 / math.sqrt(d),
                          causal=causal, block_q=block_q, block_k=block_k),
        grid=(b, n, s // block_k, s // block_q),
        in_specs=[qb2, kvb2, kvb2, qb2, rowb2, rowb2],
        out_specs=[kvb2, kvb2],
        out_shape=[_sds((b, n, s, d), k.dtype, k),
                   _sds((b, n, s, d), v.dtype, v)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper, [b, n, s, d]
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bnsd(q, k, v, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, causal, block_q, block_k)


_flash_bnsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_val(q, k, v, causal=True, block_size=512,
                        block_q=None, block_k=None):
    """Causal flash attention on [b, s, n, d] arrays → [b, s, n, d].

    Value-level (raw jax arrays); Tensor-level wrappers live in
    nn/functional/flash_attention.py. Fallback is the caller's job —
    check flash_attention_supported() first. Explicit ``block_q`` /
    ``block_k`` pin the tiles (both must divide s); otherwise dispatch
    consults the autotune cache under FLAGS_kernel_autotune and falls
    back to the ``_pick_block`` ladder.
    """
    b, s, n, d = q.shape
    if block_q is not None or block_k is not None:
        bq = int(block_q or block_size)
        bk = int(block_k or block_size)
        if not flash_attention_supported(q.shape, block_q=bq, block_k=bk):
            raise ValueError(
                f"flash attention: blocks ({bq}, {bk}) invalid for seq "
                f"len {s} (both must divide it and be >= 8)")
    else:
        bq, bk, _src = _tuned_blocks(q.shape, q.dtype, bool(causal),
                                     block_size)
        if bq < 8 or bk < 8:
            raise ValueError(
                f"flash attention: no valid block for seq len {s}")
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash_bnsd(qt, kt, vt, bool(causal), bq, bk)
    return jnp.transpose(out, (0, 2, 1, 3))


def _mesh_flash_specs(shape):
    """(mesh_active, mesh, PartitionSpec) for running the kernel under the
    ambient framework mesh. mesh_active False → call directly (no mesh);
    True with spec None → a mesh IS active but the shape is unshardable
    (the kernel must NOT run — Mosaic custom calls cannot be
    auto-partitioned by GSPMD; under a mesh the kernel must go through
    shard_map with batch over the dp/ZeRO axes and heads over 'model')."""
    from ..distributed import mesh as mesh_mod
    from ..distributed.mesh import AXIS_DATA, AXIS_MODEL, AXIS_SHARD

    m = mesh_mod.get_mesh()
    if m is None or m.size <= 1:
        return False, None, None
    from jax.sharding import PartitionSpec as P

    b, s, n, d = shape
    batch_axes = tuple(a for a in (AXIS_DATA, AXIS_SHARD)
                       if a in m.axis_names and m.shape[a] > 1)
    head_ax = (AXIS_MODEL if AXIS_MODEL in m.axis_names
               and m.shape[AXIS_MODEL] > 1 else None)
    bdeg = 1
    for a in batch_axes:
        bdeg *= m.shape[a]
    ndeg = m.shape[head_ax] if head_ax else 1
    if b % bdeg or n % ndeg:
        return True, None, None  # unshardable shape under this mesh
    if not flash_attention_supported((b // bdeg, s, n // ndeg, d)):
        return True, None, None  # per-shard shape defeats the kernel
    return True, m, P(batch_axes or None, None, head_ax, None)


def flash_attention_sharded_ok(shape) -> bool:
    """Can flash_attention_val_auto run this [b, s, n, d] shape — on the
    ambient mesh if one is active, directly otherwise?"""
    active, mesh, _spec = _mesh_flash_specs(tuple(shape))
    if not active:
        return flash_attention_supported(tuple(shape))
    return mesh is not None


def flash_attention_val_auto(q, k, v, causal=True, block_size=512):
    """flash_attention_val that is safe under an active mesh: wraps the
    pallas call in shard_map with batch/head partitioning so GSPMD never
    sees an unpartitionable Mosaic call. Check flash_attention_sharded_ok
    first; raises ValueError (not an opaque Mosaic compile crash) when a
    mesh is active but the shape cannot be sharded onto it."""
    active, mesh, spec = _mesh_flash_specs(q.shape)
    if not active:
        return flash_attention_val(q, k, v, causal=causal,
                                   block_size=block_size)
    if mesh is None:
        raise ValueError(
            f"flash attention shape {tuple(q.shape)} cannot be sharded "
            f"onto the active mesh — batch/heads must divide the "
            f"data*sharding / model degrees (check "
            f"flash_attention_sharded_ok first)")
    fn = functools.partial(flash_attention_val, causal=causal,
                           block_size=block_size)
    from ..distributed import mesh as mesh_mod

    return mesh_mod.compat_shard_map(fn, mesh, (spec, spec, spec),
                                     spec)(q, k, v)

"""Fused blockwise dequantize + optimizer-update pallas TPU kernel.

The ``FusedFlatUpdater`` inner loop today composes jnp: decode the summed
int8/fp8-block payload back to fp32 (``grad_comm.block_decode``), run the
optimizer's elementwise ``_update`` rule, write the new parameters — three
HBM round trips over the same ~25MB flat bucket. This kernel streams the
bucket once: payload + per-block scales (+ optional error-feedback
residual) + parameters + moment slots ride HBM→VMEM tile by tile, the
dequant and the Adam/AdamW/Momentum/SGD update run in VMEM, and the new
parameters and moments come out — one pass.

Equivalence contract (what the property tests pin): the kernel replicates
the EXACT op sequence of ``optimizer._update`` composed with
``FusedFlatUpdater._bucket_fn``'s casts — fp32 math, the same scalar
pre-reductions (``lr*lm``, ``1-beta_pow``) computed with the same jnp ops
outside the kernel — and the bf16 path reproduces its exact cast chain
(grad → param dtype → fp32). The dequant entry replicates
``block_decode``'s chain: ``q*scale → /world → bucket dtype → param
dtype → fp32``. Documented tolerance: dequantized payload values are
EXACT (same fp32 products); the fp32 update matches the jnp composition
bit-for-bit up to XLA's fma-contraction freedom — the two graph shapes
may contract isolated ``a*b ± c`` elements differently, and through
Adam's divide/sqrt chain that amplifies to **a few ulp on isolated
elements** (the tests pin ulp distance ≤ 8 across the whole property
grid with > 99.9% of elements exactly equal; bf16 rounding collapses
the difference entirely). With ``FLAGS_kernel_autotune`` unset this
module is never entered and the jnp path is byte-for-byte the
pre-ISSUE-13 one.

Layout: flat buckets fold to ``(rows, 128)`` lanes, zero-padded; the grid
walks row tiles of ``tile`` rows (the autotunable parameter, family
``"fused_update"``); per-block scales ride as a ``(rows, 1)`` column so
the scale traffic stays 1/128th of the payload. Interpret mode resolves
through the shared ``target_platform()`` seam (rule K001).
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

__all__ = ["FUSED_RULES", "rule_spec", "fused_update_flat",
           "fused_dequant_update_flat", "bucket_update_fn",
           "DEFAULT_TILE"]

_LANES = 128
DEFAULT_TILE = 8          # rows per grid step — today's (pre-tuner) default

# optimizer class name -> fused kernel rule kind
FUSED_RULES = {"SGD": "sgd", "Momentum": "momentum", "Adam": "adam",
               "AdamW": "adamw"}


def _interpret() -> bool:
    from ...framework.target import target_platform

    return target_platform() != "tpu"


def rule_spec(optimizer) -> Optional[Tuple[str, dict]]:
    """(kind, hyper) when ``optimizer``'s update rule has a fused pallas
    form, else None (caller falls back to the jnp composition)."""
    kind = FUSED_RULES.get(type(optimizer).__name__)
    if kind is None:
        return None
    if kind == "sgd":
        return kind, {}
    if kind == "momentum":
        return kind, {"momentum": float(optimizer._momentum),
                      "nesterov": bool(optimizer._nesterov)}
    return kind, {"beta1": float(optimizer._beta1),
                  "beta2": float(optimizer._beta2),
                  "eps": float(optimizer._epsilon)}


def _slot_names(kind) -> Tuple[str, ...]:
    if kind == "momentum":
        return ("velocity",)
    if kind in ("adam", "adamw"):
        return ("moment1", "moment2")
    return ()


# ------------------------------------------------------------------ kernels

def _update_math(p, g, slot_vals, svec, *, kind, hyper, wd):
    """The shared in-VMEM update: mirrors optimizer._update line for line
    (same expression shapes and evaluation order — the bit-identity
    contract). ``svec`` carries the scalar pre-reductions. Returns
    (new_p_f32, [new_slot_arrays])."""
    if kind == "sgd":
        if wd:
            g = g + wd * p
        return p - svec[0] * g, []
    if kind == "momentum":
        mom = hyper["momentum"]
        if wd:
            g = g + wd * p
        v = mom * slot_vals[0] + g
        if hyper["nesterov"]:
            return p - svec[0] * (g + mom * v), [v]
        return p - svec[0] * v, [v]
    beta1, beta2, eps = hyper["beta1"], hyper["beta2"], hyper["eps"]
    if wd and kind == "adam":
        g = g + wd * p
    m1 = beta1 * slot_vals[0] + (1 - beta1) * g
    m2 = beta2 * slot_vals[1] + (1 - beta2) * g * g
    mhat = m1 / svec[1]
    vhat = m2 / svec[2]
    new_p = p - svec[0] * mhat / (jnp.sqrt(vhat) + eps)
    if wd and kind == "adamw":
        new_p = new_p - svec[0] * wd * p
    return new_p, [m1, m2]


def _plain_kernel(s_ref, g_ref, p_ref, *refs, kind, hyper, wd, n_slots):
    slot_refs, out_refs = refs[:n_slots], refs[n_slots:]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    svec = [s_ref[i] for i in range(s_ref.shape[0])]
    new_p, new_slots = _update_math(p, g, [r[...] for r in slot_refs],
                                    svec, kind=kind, hyper=hyper, wd=wd)
    out_refs[0][...] = new_p.astype(out_refs[0].dtype)
    for r, v in zip(out_refs[1:], new_slots):
        r[...] = v


def _dequant_kernel(s_ref, q_ref, srow_ref, *refs, kind, hyper, wd,
                    n_slots, world, bucket_dtype, has_residual):
    refs = list(refs)
    res_ref = refs.pop(0) if has_residual else None
    p_ref = refs[0]
    slot_refs = refs[1:1 + n_slots]
    out_refs = refs[1 + n_slots:]
    p = p_ref[...].astype(jnp.float32)
    # block_decode's chain: q*scale -> /world -> bucket dtype, then
    # _bucket_fn's grad->param-dtype cast, then _update's f32 lift
    vals = q_ref[...].astype(jnp.float32) * srow_ref[...]
    gdec = vals / world
    if res_ref is not None:
        gdec = gdec + res_ref[...]
    g = gdec.astype(bucket_dtype).astype(p_ref.dtype).astype(jnp.float32)
    svec = [s_ref[i] for i in range(s_ref.shape[0])]
    new_p, new_slots = _update_math(p, g, [r[...] for r in slot_refs],
                                    svec, kind=kind, hyper=hyper, wd=wd)
    out_refs[0][...] = new_p.astype(out_refs[0].dtype)
    for r, v in zip(out_refs[1:], new_slots):
        r[...] = v


def _sds(shape, dtype, like):
    """vma-carrying ShapeDtypeStruct (see ops/flash_attention.py): keeps
    the pallas_call legal inside vma-tracked shard_map regions."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)
    if not vma:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _resolve_tile(n: int, dtype, tile: Optional[int]) -> int:
    if tile is not None:
        return int(tile)
    params = autotune.lookup("fused_update", (int(n),), dtype)
    if params:
        t = int(params.get("tile", 0))
        if t >= 1:
            return t
        autotune.count_dispatch("fused_update", "fallback")
    return DEFAULT_TILE


def _scalar_prep(kind, hyper, slots, lr, lm):
    """The scalar pre-reductions, with the same jnp ops the reference
    update uses (bit-identity): lr*lm, and for adam the stepped beta
    powers and their 1-x denominators."""
    lr_lm = lr * lm
    if kind in ("adam", "adamw"):
        b1p = slots["beta1_pow"] * hyper["beta1"]
        b2p = slots["beta2_pow"] * hyper["beta2"]
        svec = jnp.stack([lr_lm, 1 - b1p, 1 - b2p]).astype(jnp.float32)
        return svec, {"beta1_pow": b1p, "beta2_pow": b2p}
    return jnp.reshape(lr_lm, (1,)).astype(jnp.float32), {}


def _geometry(n: int, tile: int):
    rows = max(1, -(-n // _LANES))
    tile = max(1, min(int(tile), rows))
    R = -(-rows // tile) * tile
    return rows, tile, R, R * _LANES - n


def _fold(x, R, fill=0):
    pad = R * _LANES - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
    return x.reshape(R, _LANES)


def fused_update_flat(flat_p, flat_g, slots: Dict, lr, *, kind: str,
                      hyper: dict, lm: float = 1.0, wd: float = 0.0,
                      tile: Optional[int] = None):
    """One fused update over a flat bucket — the non-dequant entry,
    drop-in for ``FusedFlatUpdater._bucket_fn``'s jnp body. Returns
    ``(new_p, new_slots)`` with the update rule's exact math
    (bit-identical for fp32; bf16 reproduces the jnp cast chain)."""
    n = int(flat_p.shape[0])
    names = _slot_names(kind)
    _, tile, R, _ = _geometry(n, _resolve_tile(n, flat_p.dtype, tile))
    svec, scalar_slots = _scalar_prep(kind, hyper, slots, lr, lm)
    g = _fold(flat_g.astype(flat_p.dtype), R)     # _bucket_fn's cast
    p2 = _fold(flat_p, R)
    slot2 = [_fold(slots[nm], R) for nm in names]
    blk = pl.BlockSpec((tile, _LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_plain_kernel, kind=kind, hyper=hyper, wd=wd,
                          n_slots=len(names)),
        grid=(R // tile,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [blk] * (2 + len(names)),
        out_specs=[blk] * (1 + len(names)),
        out_shape=[_sds((R, _LANES), flat_p.dtype, flat_p)]
        + [_sds((R, _LANES), jnp.float32, flat_p)] * len(names),
        interpret=_interpret(),
    )(svec, g, p2, *slot2)
    new_slots = {nm: o.reshape(-1)[:n] for nm, o in zip(names, out[1:])}
    new_slots.update(scalar_slots)
    return out[0].reshape(-1)[:n], new_slots


def fused_dequant_update_flat(flat_p, q, scales, world: int, slots: Dict,
                              lr, *, kind: str, hyper: dict,
                              block_size: int, bucket_dtype=None,
                              lm: float = 1.0, wd: float = 0.0,
                              residual=None, tile: Optional[int] = None):
    """Fused ``block_decode`` + update: the summed blockwise payload ``q``
    (``(n_blocks, block_size)`` int32/fp32 carrier) and the per-block fp32
    ``scales`` go in; the decoded-AVG gradient never materializes in HBM.
    ``residual`` (fp32, bucket length), when given, is added to the
    decoded gradient in fp32 before the bucket-dtype cast. Falls back to
    the jnp decode feeding :func:`fused_update_flat` when ``block_size``
    does not fold to whole 128-lane rows (ragged tiling)."""
    n = int(flat_p.shape[0])
    bucket_dtype = jnp.dtype(bucket_dtype or flat_p.dtype)
    if block_size % _LANES:
        from ...distributed.grad_comm import block_decode

        g = block_decode(q, scales, world, bucket_dtype, n)
        if residual is not None:
            g = (g.astype(jnp.float32) + residual).astype(bucket_dtype)
        return fused_update_flat(flat_p, g, slots, lr, kind=kind,
                                 hyper=hyper, lm=lm, wd=wd, tile=tile)
    names = _slot_names(kind)
    rows, tile, R, _ = _geometry(n, _resolve_tile(n, flat_p.dtype, tile))
    svec, scalar_slots = _scalar_prep(kind, hyper, slots, lr, lm)
    carrier = jnp.int32 if q.dtype == jnp.int32 else jnp.float32
    q2 = _fold(q.reshape(-1)[:n].astype(carrier), R)
    # one scale per 128-lane row: row i lives in block (i*128)//block_size
    row_idx = (jnp.arange(rows) * _LANES) // block_size
    srow = jnp.take(scales.astype(jnp.float32), row_idx)
    if R > rows:
        srow = jnp.concatenate(
            [srow, jnp.ones((R - rows,), jnp.float32)])
    srow = srow.reshape(R, 1)
    arrs = [q2, srow]
    specs = [pl.BlockSpec((tile, _LANES), lambda i: (i, 0)),
             pl.BlockSpec((tile, 1), lambda i: (i, 0))]
    if residual is not None:
        arrs.append(_fold(residual.astype(jnp.float32), R))
        specs.append(pl.BlockSpec((tile, _LANES), lambda i: (i, 0)))
    blk = pl.BlockSpec((tile, _LANES), lambda i: (i, 0))
    arrs.append(_fold(flat_p, R))
    arrs.extend(_fold(slots[nm], R) for nm in names)
    specs.extend([blk] * (1 + len(names)))
    out = pl.pallas_call(
        functools.partial(_dequant_kernel, kind=kind, hyper=hyper, wd=wd,
                          n_slots=len(names), world=int(world),
                          bucket_dtype=bucket_dtype,
                          has_residual=residual is not None),
        grid=(R // tile,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + specs,
        out_specs=[blk] * (1 + len(names)),
        out_shape=[_sds((R, _LANES), flat_p.dtype, flat_p)]
        + [_sds((R, _LANES), jnp.float32, flat_p)] * len(names),
        interpret=_interpret(),
    )(svec, *arrs)
    new_slots = {nm: o.reshape(-1)[:n] for nm, o in zip(names, out[1:])}
    new_slots.update(scalar_slots)
    return out[0].reshape(-1)[:n], new_slots


def bucket_update_fn(optimizer, lm: float, wd: float):
    """``f(flat_p, flat_g, slots, lr) -> (new_p, new_slots)`` routing
    ``FusedFlatUpdater._bucket_fn`` through the fused kernel, or None
    when the optimizer's rule has no fused form (caller keeps the jnp
    path). The returned f matches the jnp body's signature and output
    dtypes exactly, so the caller's ``jax.jit(..., donate_argnums=(2,))``
    wrapping is unchanged."""
    spec = rule_spec(optimizer)
    if spec is None:
        return None
    kind, hyper = spec

    def f(flat_p, flat_g, slots, lr):
        new_p, new_s = fused_update_flat(flat_p, flat_g, slots, lr,
                                         kind=kind, hyper=hyper, lm=lm,
                                         wd=wd)
        return new_p.astype(flat_p.dtype), new_s

    return f


# ----------------------------------------------------------- tuner family

def reference_update_flat(flat_p, flat_g, slots, lr, *, kind, hyper,
                          lm=1.0, wd=0.0):
    """The pure-jnp composition the kernel replaces — the interpret-mode
    validation reference, and what the equivalence tests compare
    against (it IS optimizer._update's math on a flat bucket)."""
    g = flat_g.astype(flat_p.dtype).astype(jnp.float32)
    p32 = flat_p.astype(jnp.float32)
    svec, scalar_slots = _scalar_prep(kind, hyper, slots, lr, lm)
    new_p, new_arrs = _update_math(
        p32, g, [slots[nm] for nm in _slot_names(kind)], svec,
        kind=kind, hyper=hyper, wd=wd)
    out = dict(zip(_slot_names(kind), new_arrs))
    out.update(scalar_slots)
    return new_p.astype(flat_p.dtype), out


def _register_family():
    def candidates(p, g, slots, lr, kind, hyper, lm, wd):
        rows = -(-int(p.shape[0]) // _LANES)
        return [{"tile": t} for t in (1, 2, 4, 8, 16, 32, 64, 128)
                if t <= max(1, rows)]

    def run(params, p, g, slots, lr, kind, hyper, lm, wd):
        return fused_update_flat(p, g, dict(slots), lr, kind=kind,
                                 hyper=hyper, lm=lm, wd=wd,
                                 tile=params["tile"])

    def reference(p, g, slots, lr, kind, hyper, lm, wd):
        return reference_update_flat(p, g, dict(slots), lr, kind=kind,
                                     hyper=hyper, lm=lm, wd=wd)

    def cost(p, g, slots, lr, kind, hyper, lm, wd):
        n = float(p.shape[0])
        n_arrays = 2 + 2 * len(_slot_names(kind)) + 1
        return 12 * n, n_arrays * n * 4

    autotune.register_family(autotune.KernelFamily(
        "fused_update",
        candidates=candidates,
        default_params=lambda *a: {"tile": DEFAULT_TILE},
        run=run, reference=reference, cost=cost,
        key_shape=lambda p, *a: (int(p.shape[0]),),
        key_dtype=lambda p, *a: p.dtype,
        rtol=1e-6, atol=1e-6))


_register_family()

"""Kernel autotune harness: sweep tile/block shapes, validate, persist.

The CUDA-L2 / tensor-core-autogen recipe (PAPERS.md) applied to our pallas
kernels: a kernel *family* exposes its tunable parameters (flash
attention's ``block_q``/``block_k``, quant_matmul's m/n/k tiles, the fused
dequant+update bucket tile, the blockwise codec row tile) and the harness

  1. enumerates candidate parameter sets for a concrete input,
  2. **validates every candidate against the jnp reference op** within the
     family's tolerance — an unvalidated candidate is never eligible, no
     matter how fast it times;
  3. times eligible candidates by compiled execution on the device.
     Interpret-mode candidates (CPU tier-1, AOT hosts) are
     validated-only and NEVER timed — interpreter wall time says nothing
     about Mosaic codegen. Tests inject a ``timer`` to exercise selection;
  4. sanity-bounds every measurement against the ``cost_model`` roofline
     (:func:`cost_model.kernel_roofline`): a time below the physical bound
     is measurement noise and is rejected, not persisted;
  5. persists the winner keyed ``(kernel, shape_bucket, dtype,
     device_kind)`` in a JSON cache — ``artifacts/kernel_tune_cache.json``
     is the committed copy, ``.cache/kernel_tune_cache.json`` the runtime
     one — that :func:`lookup` consults at dispatch under
     ``FLAGS_kernel_autotune``.

Dispatch contract (the flag-off inertness guarantee): with
``FLAGS_kernel_autotune`` unset, :func:`lookup` returns ``None`` without
touching any file and every kernel runs today's defaults — the numeric
behavior is dot-for-dot the pre-autotuner one. Cache miss falls back to
the defaults; a corrupt or version-drifted cache is discarded LOUDLY (a
``warnings.warn``) and counts as ``fallback`` in the
``kernel_dispatch_total{kernel=,source=tuned|default|fallback}`` counter.

Determinism: cache keys are pure functions of (kernel, shape bucket,
dtype, device kind) — no timestamps, no ids — and the JSON dump sorts its
keys, so save→load→save round-trips byte-identically offline.
"""
from __future__ import annotations

import json
import os
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...observability.metrics import get_registry as _get_registry

__all__ = [
    "CACHE_VERSION", "KernelFamily", "FAMILIES", "register_family",
    "TuneCache", "shape_bucket", "cache_key", "current_device_kind",
    "artifact_cache_path", "runtime_cache_path", "get_runtime_cache",
    "reset_runtime_cache", "lookup", "count_dispatch", "autotune",
]

CACHE_VERSION = 1

_m_dispatch = _get_registry().counter(
    "kernel_dispatch_total",
    help="kernel dispatch decisions by parameter source",
    labels=("kernel", "source"))


def count_dispatch(kernel: str, source: str):
    """One dispatch decision into the process-global counter. ``source``
    is 'tuned' (cache hit applied), 'default' (flag off or plain cache
    miss) or 'fallback' (flag on but the cache/tuned entry was unusable —
    corrupt file, version drift, or params invalid for the live shape)."""
    _m_dispatch.labels(kernel=kernel, source=source).inc()


# --------------------------------------------------------------------- keys

def _ceil_pow2(n: int) -> int:
    n = max(1, int(n))
    p = 1
    while p < n:
        p <<= 1
    return p


def shape_bucket(shape: Sequence[int]) -> Tuple[int, ...]:
    """Bucket a concrete shape: each dim rounds UP to the next power of
    two. Nearby shapes share a tuned entry (a 1000-element bucket reuses
    the 1024 winner) while the validation step still runs on the concrete
    shape, so a bucketed winner is never applied unvalidated at tune time
    and dispatch re-checks divisibility before applying it."""
    return tuple(_ceil_pow2(d) for d in shape)


def current_device_kind() -> str:
    """PJRT device kind of the default backend ('cpu' on the host
    fallback) — one half of the cache key."""
    import jax

    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "cpu"


def _dtype_str(dtype) -> str:
    """Canonical dtype spelling for the key ('float32', not a class
    repr); composite family strings ('float32-causal') pass through."""
    if isinstance(dtype, str):
        return dtype
    import numpy as np

    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


def cache_key(kernel: str, shape: Sequence[int], dtype,
              device_kind: Optional[str] = None) -> str:
    if device_kind is None:
        device_kind = current_device_kind()
    bucket = "x".join(str(d) for d in shape_bucket(shape))
    return f"{kernel}|{bucket}|{_dtype_str(dtype)}|{device_kind}"


# -------------------------------------------------------------------- cache

def _repo_root() -> str:
    # paddle_tpu/ops/pallas/autotune.py -> repo root three levels up
    return os.path.abspath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..", ".."))


def artifact_cache_path() -> str:
    return os.path.join(_repo_root(), "artifacts", "kernel_tune_cache.json")


def runtime_cache_path() -> str:
    return os.path.join(_repo_root(), ".cache", "kernel_tune_cache.json")


class TuneCache:
    """The persisted winner table: {key: {"params", "measured_ms",
    "default_ms", "validated"}}. ``ok`` is False when a load found a
    corrupt/version-drifted file (discarded loudly; dispatch then counts
    'fallback' instead of quietly serving garbage)."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 ok: bool = True):
        self.entries: Dict[str, dict] = dict(entries or {})
        self.ok = ok

    @classmethod
    def load(cls, path: str) -> "TuneCache":
        """Load a cache file. Missing file -> empty cache (ok=True: an
        empty cache is a valid state). Corrupt JSON, wrong version, or a
        non-dict payload -> empty cache with ok=False plus a LOUD
        warning — a drifted cache must never silently pick kernels."""
        if not os.path.exists(path):
            return cls()
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError("cache payload is not an object")
            if data.get("version") != CACHE_VERSION:
                raise ValueError(
                    f"cache version {data.get('version')!r} != "
                    f"{CACHE_VERSION}")
            entries = data.get("entries")
            if not isinstance(entries, dict):
                raise ValueError("cache has no entries object")
            return cls(entries)
        except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
            warnings.warn(
                f"kernel tune cache {path!r} discarded: {e} — dispatch "
                f"falls back to default kernel parameters", stacklevel=2)
            return cls(ok=False)

    def get(self, key: str) -> Optional[dict]:
        e = self.entries.get(key)
        return e if isinstance(e, dict) and "params" in e else None

    def put(self, key: str, params: dict, measured_ms: Optional[float] = None,
            default_ms: Optional[float] = None):
        entry = {"params": dict(params), "validated": True}
        if measured_ms is not None:
            entry["measured_ms"] = round(float(measured_ms), 6)
        if default_ms is not None:
            entry["default_ms"] = round(float(default_ms), 6)
        self.entries[key] = entry

    def dump(self) -> str:
        """Deterministic JSON: sorted keys, no timestamps — two dumps of
        the same entries are byte-identical (the offline round-trip
        contract)."""
        return json.dumps({"version": CACHE_VERSION,
                           "entries": self.entries},
                          sort_keys=True, indent=1) + "\n"

    def save(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.dump())
        os.replace(tmp, path)


_runtime_cache: Optional[TuneCache] = None


def get_runtime_cache(reload: bool = False) -> TuneCache:
    """The process-wide dispatch cache: the runtime ``.cache/`` copy when
    present, else the committed artifact. Loaded once (dispatch is on hot
    paths); ``reload=True`` / :func:`reset_runtime_cache` re-read."""
    global _runtime_cache
    if _runtime_cache is None or reload:
        path = runtime_cache_path()
        if not os.path.exists(path):
            path = artifact_cache_path()
        _runtime_cache = TuneCache.load(path)
    return _runtime_cache


def reset_runtime_cache(cache: Optional[TuneCache] = None):
    """Drop (or inject, for tests) the memoized dispatch cache."""
    global _runtime_cache
    _runtime_cache = cache


def lookup(kernel: str, shape: Sequence[int], dtype,
           device_kind: Optional[str] = None) -> Optional[dict]:
    """Dispatch-side consult: the tuned parameter dict for this call
    site, or None for "use today's defaults".

    Flag off -> None immediately (and counts 'default'): the entire
    autotuner is inert without ``FLAGS_kernel_autotune``. Flag on: a
    cache hit counts 'tuned' and returns a COPY of the params (callers
    may mutate); a miss counts 'default'; an unloadable cache counts
    'fallback'. Callers that find the tuned params invalid for the live
    shape (e.g. a block that no longer divides the sequence) must call
    :func:`count_dispatch(kernel, "fallback")` and use their defaults.
    """
    from ...framework.flags import flag

    if not flag("FLAGS_kernel_autotune"):
        count_dispatch(kernel, "default")
        return None
    cache = get_runtime_cache()
    if not cache.ok:
        count_dispatch(kernel, "fallback")
        return None
    entry = cache.get(cache_key(kernel, shape, dtype, device_kind))
    if entry is None:
        count_dispatch(kernel, "default")
        return None
    count_dispatch(kernel, "tuned")
    return dict(entry["params"])


# ----------------------------------------------------------------- families

class KernelFamily:
    """One tunable kernel family.

    candidates(*args) -> [param dict, ...] valid for these concrete args
    default_params(*args) -> the pre-autotuner dispatch choice
    run(params, *args) -> kernel output pytree (through the
        ``target_platform()`` interpret seam, like every dispatch site)
    reference(*args) -> jnp reference output pytree
    cost(*args) -> (flops, bytes_accessed) for the roofline bound
    key_shape(*args) -> the shape tuple the cache key buckets
    key_dtype(*args) -> the dtype half of the key
    rtol/atol: validation tolerance vs the reference
    """

    def __init__(self, name: str, *, candidates: Callable,
                 default_params: Callable, run: Callable,
                 reference: Callable, cost: Callable, key_shape: Callable,
                 key_dtype: Callable, rtol: float = 1e-5,
                 atol: float = 1e-5):
        self.name = name
        self.candidates = candidates
        self.default_params = default_params
        self.run = run
        self.reference = reference
        self.cost = cost
        self.key_shape = key_shape
        self.key_dtype = key_dtype
        self.rtol = rtol
        self.atol = atol


FAMILIES: Dict[str, KernelFamily] = {}


def register_family(family: KernelFamily) -> KernelFamily:
    FAMILIES[family.name] = family
    return family


def _leaves(x) -> List[Any]:
    import jax

    return jax.tree_util.tree_leaves(x)


def _validates(family: KernelFamily, out, ref) -> bool:
    import numpy as np

    a, b = _leaves(out), _leaves(ref)
    if len(a) != len(b):
        return False
    for xa, xb in zip(a, b):
        xa = np.asarray(xa, dtype=np.float64)
        xb = np.asarray(xb, dtype=np.float64)
        if xa.shape != xb.shape:
            return False
        if not np.allclose(xa, xb, rtol=family.rtol, atol=family.atol):
            return False
    return True


def _can_time_on_device() -> bool:
    """Real timing needs compiled (Mosaic) execution — only when the
    compile target is a live TPU. Interpret-mode timings are meaningless
    and the contract forbids them."""
    from ...framework.target import target_platform

    return target_platform() == "tpu"


def _device_timer(fn: Callable[[], Any], repeats: int) -> float:
    """Median-of-repeats wall seconds of ``fn`` with device sync."""
    import time

    import jax

    def once() -> float:
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    once()  # warmup / compile outside the clock
    return sorted(once() for _ in range(max(1, repeats)))[repeats // 2]


def autotune(kernel: str, *args, cache: Optional[TuneCache] = None,
             timer: Optional[Callable] = None, repeats: int = 5,
             persist: bool = True, device_kind: Optional[str] = None,
             cache_path: Optional[str] = None) -> dict:
    """Sweep one family over concrete inputs; returns the report dict.

    ``timer(params, fn)`` -> seconds overrides on-device measurement
    (tests inject deterministic timers; interpret-mode runs without a
    timer validate every candidate but select no winner). A winner is
    persisted only when it is validated, roofline-sane, and differs from
    the default parameters.
    """
    family = FAMILIES[kernel]
    if device_kind is None:
        device_kind = current_device_kind()
    ref = family.reference(*args)
    default = family.default_params(*args)
    flops, nbytes = family.cost(*args)
    from ...cost_model import kernel_roofline

    floor_s = kernel_roofline(flops, nbytes, device_kind)
    can_time = timer is not None or _can_time_on_device()

    rows = []
    for params in family.candidates(*args):
        row = {"params": dict(params), "validated": False, "time_s": None,
               "rejected": None}
        rows.append(row)
        try:
            out = family.run(params, *args)
        except Exception as e:  # a candidate that fails to lower is just
            row["rejected"] = f"run failed: {type(e).__name__}"
            continue            # ineligible, not a harness error
        if not _validates(family, out, ref):
            row["rejected"] = "reference mismatch"
            continue
        row["validated"] = True
        if not can_time:
            continue            # interpret mode: validated-only, never timed
        if timer is not None:
            t = float(timer(params, lambda p=params: family.run(p, *args)))
        else:
            t = _device_timer(lambda p=params: family.run(p, *args), repeats)
        if t < floor_s:
            row["rejected"] = "below roofline (noise)"
            continue
        row["time_s"] = t

    timed = [r for r in rows if r["time_s"] is not None]
    winner = min(timed, key=lambda r: r["time_s"]) if timed else None
    default_row = next((r for r in rows if r["params"] == default), None)
    key = cache_key(kernel, family.key_shape(*args),
                    family.key_dtype(*args), device_kind)
    persisted = False
    if winner is not None and winner["params"] != default and persist:
        if cache is None:
            cache = get_runtime_cache()
        cache.put(key, winner["params"],
                  measured_ms=winner["time_s"] * 1e3,
                  default_ms=(default_row["time_s"] * 1e3
                              if default_row and default_row["time_s"]
                              else None))
        cache.save(cache_path or runtime_cache_path())
        reset_runtime_cache(cache)
        persisted = True
    return {
        "kernel": kernel,
        "key": key,
        "device_kind": device_kind,
        "roofline_floor_s": floor_s,
        "n_candidates": len(rows),
        "n_validated": sum(1 for r in rows if r["validated"]),
        "n_timed": len(timed),
        "n_rejected_roofline": sum(1 for r in rows
                                   if r["rejected"] == "below roofline "
                                                       "(noise)"),
        "default_params": default,
        "winner_params": dict(winner["params"]) if winner else None,
        "winner_ms": (winner["time_s"] * 1e3 if winner else None),
        "default_ms": (default_row["time_s"] * 1e3
                       if default_row and default_row["time_s"] else None),
        "persisted": persisted,
        "candidates": rows,
    }

"""Blockwise quantize/dequantize codec kernels (pallas TPU).

The PR-8 EQuARX blockwise wire codecs (``grad_comm.block_encode`` /
``block_decode``) are pure jnp — correct everywhere, but on TPU the
encode's divide+round+clip+double-cast chain and the decode's
multiply+scale-broadcast each cost XLA a full HBM round trip over a
~25MB bucket between the collectives. These kernels run the same math as
one VMEM pass per direction; the pure-jnp pair stays the interpret-mode
reference (and the dispatch fallback), so every ZeRO-2/3 and
crash→resume parity guarantee keeps its bit-for-bit meaning:

  int8_block: bit-identical payload integers (round/clip on the same
      fp32 values);
  fp8_block:  bit-identical float8_e4m3fn wire values (same cast).

Dispatch: ``grad_comm._block_kernel_ops()`` selects this module only
under ``FLAGS_kernel_autotune`` when the compile target is TPU
(:func:`use_tpu_kernels`); ragged geometries (block_size not a multiple
of the 128-lane width) fall back to the jnp reference internally. The
row tile is the autotunable parameter (family ``"block_codec"``).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

__all__ = ["use_tpu_kernels", "block_encode", "block_decode",
           "DEFAULT_TILE"]

_LANES = 128
DEFAULT_TILE = 8
_FP8_WIRE = getattr(jnp, "float8_e4m3fn", None)


def _interpret() -> bool:
    from ...framework.target import target_platform

    return target_platform() != "tpu"


def use_tpu_kernels() -> bool:
    """True when the compile target is TPU — the only platform where the
    Mosaic codec kernels beat the XLA-fused jnp pair."""
    from ...framework.target import target_platform

    return target_platform() == "tpu"


def _sds(shape, dtype, like):
    """vma-carrying ShapeDtypeStruct (see ops/flash_attention.py): keeps
    the pallas_call legal inside vma-tracked shard_map regions (the
    traced ZeRO-2 reduce_scatter path runs these under shard_map)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        return jax.ShapeDtypeStruct(shape, dtype)
    if not vma:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _resolve_tile(nb: int, dtype, tile: Optional[int]) -> int:
    if tile is not None:
        return int(tile)
    params = autotune.lookup("block_codec", (int(nb),), dtype)
    if params:
        t = int(params.get("tile", 0))
        if t >= 1:
            return t
        autotune.count_dispatch("block_codec", "fallback")
    return DEFAULT_TILE


def _pad_rows(x, tile):
    nb = x.shape[0]
    tile = max(1, min(int(tile), nb))
    R = -(-nb // tile) * tile
    if R > nb:
        pad = jnp.zeros((R - nb,) + x.shape[1:], x.dtype)
        x = jnp.concatenate([x, pad])
    return x, R, tile


# ------------------------------------------------------------------- encode

def _encode_kernel(x_ref, s_ref, q_ref, *, codec):
    q = x_ref[...] / s_ref[...]
    if codec == "int8_block":
        q_ref[...] = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8) \
            .astype(jnp.int32)
    else:
        q_ref[...] = q.astype(_FP8_WIRE).astype(jnp.float32)


def block_encode(flat, scales, block_size: int, codec: str,
                 tile: Optional[int] = None):
    """Drop-in for ``grad_comm.block_encode`` (same signature, same
    payload bits): blockwise quantize with the shared scales as one VMEM
    pass. Ragged block sizes fall back to the jnp reference."""
    from ...distributed import grad_comm as _gc

    if block_size % _LANES or codec not in ("int8_block", "fp8_block") \
            or (codec == "fp8_block" and _FP8_WIRE is None):
        return _gc.block_encode(flat, scales, block_size, codec)
    x = _gc._as_blocks(flat, block_size)                 # (nb, bs) fp32
    nb = int(x.shape[0])
    s = scales.astype(jnp.float32).reshape(nb, 1)
    tile = _resolve_tile(nb, jnp.int8 if codec == "int8_block"
                         else _FP8_WIRE, tile)
    x, R, tile = _pad_rows(x, tile)
    if s.shape[0] < R:
        # pad scales with ONES (not zeros): padded rows are all-zero
        # payload and a zero scale would make them 0/0 = NaN
        s = jnp.concatenate([s, jnp.ones((R - s.shape[0], 1), s.dtype)])
    out_dtype = jnp.int32 if codec == "int8_block" else jnp.float32
    bs = int(x.shape[1])
    q = pl.pallas_call(
        functools.partial(_encode_kernel, codec=codec),
        grid=(R // tile,),
        in_specs=[pl.BlockSpec((tile, bs), lambda i: (i, 0)),
                  pl.BlockSpec((tile, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, bs), lambda i: (i, 0)),
        out_shape=_sds((R, bs), out_dtype, flat),
        interpret=_interpret(),
    )(x, s)
    return q[:nb]


# ------------------------------------------------------------------- decode

def _decode_kernel(q_ref, s_ref, o_ref, *, world):
    vals = q_ref[...].astype(jnp.float32) * s_ref[...]
    o_ref[...] = (vals / world).astype(o_ref.dtype)


def block_decode(q_sum, scales, world: int, dtype, numel: int,
                 tile: Optional[int] = None):
    """Drop-in for ``grad_comm.block_decode``: dequantize the summed
    payload back to the grad dtype (AVG) in one VMEM pass."""
    from ...distributed import grad_comm as _gc

    nb, bs = int(q_sum.shape[0]), int(q_sum.shape[1])
    if bs % _LANES:
        return _gc.block_decode(q_sum, scales, world, dtype, numel)
    s = scales.astype(jnp.float32).reshape(nb, 1)
    tile = _resolve_tile(nb, jnp.dtype(dtype), tile)
    q, R, tile = _pad_rows(q_sum, tile)
    if s.shape[0] < R:
        s = jnp.concatenate([s, jnp.ones((R - s.shape[0], 1), s.dtype)])
    out = pl.pallas_call(
        functools.partial(_decode_kernel, world=world),
        grid=(R // tile,),
        in_specs=[pl.BlockSpec((tile, bs), lambda i: (i, 0)),
                  pl.BlockSpec((tile, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, bs), lambda i: (i, 0)),
        out_shape=_sds((R, bs), jnp.dtype(dtype), q_sum),
        interpret=_interpret(),
    )(q, s)
    return out[:nb].reshape(-1)[:numel]


# ----------------------------------------------------------- tuner family

def _register_family():
    def _ref(params_ignored, flat, scales, block_size, codec, world, numel):
        from ...distributed import grad_comm as _gc

        q = _gc.block_encode(flat, scales, block_size, codec)
        return q, _gc.block_decode(q, scales, world, jnp.float32, numel)

    def candidates(flat, scales, block_size, codec, world, numel):
        nb = int(scales.shape[0])
        return [{"tile": t} for t in (1, 2, 4, 8, 16, 32, 64)
                if t <= max(1, nb)]

    def run(params, flat, scales, block_size, codec, world, numel):
        q = block_encode(flat, scales, block_size, codec,
                         tile=params["tile"])
        return q, block_decode(q, scales, world, jnp.float32, numel,
                               tile=params["tile"])

    def cost(flat, scales, block_size, codec, world, numel):
        n = float(flat.shape[0])
        return 6 * n, (4 + 1 + 1 + 4) * n

    autotune.register_family(autotune.KernelFamily(
        "block_codec",
        candidates=candidates,
        default_params=lambda *a: {"tile": DEFAULT_TILE},
        run=run,
        reference=lambda *a: _ref(None, *a),
        cost=cost,
        key_shape=lambda flat, scales, *a: (int(scales.shape[0]),),
        key_dtype=lambda flat, scales, block_size, codec, *a: codec,
        rtol=0.0, atol=0.0))       # codec payloads must be bit-identical


_register_family()

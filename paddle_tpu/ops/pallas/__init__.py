"""paddle_tpu.ops.pallas — the kernel-performance layer (ISSUE 13).

Three pieces grow raw per-chip math throughput (BENCH_r05: 104.8k
measured vs ~444k roofline tokens/s/chip):

- ``autotune.py`` — a CUDA-L2-spirit sweep harness over kernel tile
  parameters: validate every candidate against the jnp reference, time
  compiled execution on device (interpret-mode candidates are
  validated-only), sanity-bound timings against ``cost_model`` rooflines,
  persist winners per ``(kernel, shape_bucket, dtype, device_kind)`` in
  ``artifacts/kernel_tune_cache.json`` (+ a ``.cache/`` runtime copy)
  consulted at dispatch under ``FLAGS_kernel_autotune``.
- ``fused_update.py`` — fused blockwise dequantize + optimizer update
  over flat grad_comm buckets (the ``FusedFlatUpdater`` inner loop as
  one VMEM pass).
- ``codec.py`` — the PR-8 blockwise quantize/dequantize wire codecs as
  pallas kernels for TPU, pure-jnp pair kept as the interpret reference.

Importing this package registers all four tuner families (the two new
kernels plus flash_attention and quant_matmul via ``families.py``).
"""
from __future__ import annotations

from . import autotune  # noqa: F401
from . import codec  # noqa: F401
from . import families  # noqa: F401
from . import fused_update  # noqa: F401
from .autotune import (FAMILIES, TuneCache, autotune as autotune_sweep,
                       cache_key, count_dispatch, lookup, shape_bucket)
from .codec import block_decode, block_encode, use_tpu_kernels
from .fused_update import (bucket_update_fn, fused_dequant_update_flat,
                           fused_update_flat, rule_spec)

__all__ = [
    "FAMILIES", "TuneCache", "autotune", "autotune_sweep", "cache_key",
    "codec", "count_dispatch", "families", "fused_update", "lookup",
    "shape_bucket", "block_decode", "block_encode", "use_tpu_kernels",
    "bucket_update_fn", "fused_dequant_update_flat", "fused_update_flat",
    "rule_spec",
]

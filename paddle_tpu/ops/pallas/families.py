"""Tuner families for the pre-existing pallas kernels.

flash_attention and quant_matmul predate the autotuner (their kernels
live in ``ops/``); this module only teaches the harness their parameter
spaces and references. The fused_update and block_codec families register
themselves from their own kernel modules.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from . import autotune

__all__ = ["flash_candidate_blocks"]

_FLASH_BLOCKS = (512, 256, 128, 64, 32, 16, 8)


def flash_candidate_blocks(s: int):
    """Valid (block_q, block_k) pairs for sequence length ``s`` — every
    ladder block that divides s, combined independently (the satellite
    point: q and k tiles need not be equal; a long-seq kernel often wants
    a wide k tile against a narrow q tile)."""
    valid = [b for b in _FLASH_BLOCKS if b <= s and s % b == 0]
    return [(bq, bk) for bq in valid for bk in valid]


def _flash_reference(q, k, v, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _register_flash():
    from ..flash_attention import _pick_block, flash_attention_val

    def candidates(q, k, v, causal):
        return [{"block_q": bq, "block_k": bk}
                for bq, bk in flash_candidate_blocks(int(q.shape[1]))]

    def default_params(q, k, v, causal):
        blk = _pick_block(int(q.shape[1]), 512)
        return {"block_q": blk, "block_k": blk}

    def run(params, q, k, v, causal):
        return flash_attention_val(q, k, v, causal=causal,
                                   block_q=params["block_q"],
                                   block_k=params["block_k"])

    def cost(q, k, v, causal):
        b, s, n, d = q.shape
        flops = 4.0 * b * n * s * s * d * (0.5 if causal else 1.0)
        nbytes = 4.0 * b * s * n * d * q.dtype.itemsize
        return flops, nbytes

    autotune.register_family(autotune.KernelFamily(
        "flash_attention",
        candidates=candidates,
        default_params=default_params,
        run=run,
        reference=lambda q, k, v, causal: _flash_reference(q, k, v, causal),
        cost=cost,
        key_shape=lambda q, k, v, causal: tuple(int(x) for x in q.shape),
        key_dtype=lambda q, k, v, causal: (
            f"{q.dtype}-{'causal' if causal else 'full'}"),
        rtol=2e-2, atol=2e-2))   # bf16-wide tolerance; fp32 is ~1e-5


def _register_quant_matmul():
    from ..quant_matmul import quant_matmul

    tiles = (64, 128, 256, 512)

    def candidates(x, qw, scales):
        m, k = x.shape
        _, n = qw.shape
        return [{"block_m": bm, "block_n": bn, "block_k": bk}
                for bm in tiles if m % min(bm, m) == 0
                for bn in tiles if n % min(bn, n) == 0
                for bk in tiles if k % min(bk, k) == 0]

    def run(params, x, qw, scales):
        return quant_matmul(x, qw, scales, **params)

    def reference(x, qw, scales):
        return (x.astype(jnp.float32)
                @ (qw.astype(jnp.float32) * scales)).astype(x.dtype)

    def cost(x, qw, scales):
        m, k = x.shape
        _, n = qw.shape
        return 2.0 * m * n * k, (m * k * 4.0 + k * n * 1.0 + n * 4.0
                                 + m * n * 4.0)

    autotune.register_family(autotune.KernelFamily(
        "quant_matmul",
        candidates=candidates,
        default_params=lambda x, qw, scales: {
            "block_m": 256, "block_n": 256, "block_k": 512},
        run=run, reference=reference, cost=cost,
        key_shape=lambda x, qw, scales: (int(x.shape[0]), int(x.shape[1]),
                                         int(qw.shape[1])),
        key_dtype=lambda x, qw, scales: x.dtype,
        rtol=1e-4, atol=1e-3))


_register_flash()
_register_quant_matmul()

"""paddle.signal — STFT/ISTFT (parity: python/paddle/signal.py over
operators/spectral ops; frame+matmul formulation keeps the hot loop on the
MXU/FFT units)."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.autograd import call_op as op
from .framework.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _frames_tl(x, frame_length, hop_length):
    """Internal layout: time on the last axis → (..., num_frames, frame_len)."""
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])
    return x[..., idx]


def _frame_kernel(x, frame_length, hop_length, axis):
    """Public (Paddle) layout: axis=-1 → (..., frame_length, num_frames);
    axis=0 → (num_frames, frame_length, ...). Reference: signal.py frame."""
    if axis in (0,) and x.ndim > 0:
        x = jnp.moveaxis(x, 0, -1)
        out = _frames_tl(x, frame_length, hop_length)  # (..., nf, fl)
        return jnp.moveaxis(out, (-2, -1), (0, 1))
    out = _frames_tl(x, frame_length, hop_length)
    return jnp.swapaxes(out, -1, -2)  # (..., fl, nf)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    return op(_frame_kernel, x, frame_length=frame_length,
              hop_length=hop_length, axis=axis, op_name="frame")


def _overlap_add_tl(x, hop_length):
    # x: (..., num_frames, frame_length) → (..., out_len)
    num_frames, frame_length = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    pos = (hop_length * jnp.arange(num_frames)[:, None]
           + jnp.arange(frame_length)[None, :]).reshape(-1)
    flat = x.reshape(x.shape[:-2] + (-1,))
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    return out.at[..., pos].add(flat)


def _overlap_add_kernel(x, hop_length, axis):
    """Paddle layout: axis=-1 → input (..., frame_length, num_frames);
    axis=0 → input (num_frames, frame_length, ...) — frame()'s outputs
    roundtrip for both axes."""
    if axis == 0:
        if x.ndim > 2:
            x = jnp.moveaxis(x, (0, 1), (-2, -1))  # (..., nf, fl)
            return jnp.moveaxis(_overlap_add_tl(x, hop_length), -1, 0)
        return _overlap_add_tl(x, hop_length)
    return _overlap_add_tl(jnp.swapaxes(x, -1, -2), hop_length)


def overlap_add(x, hop_length, axis=-1, name=None):
    return op(_overlap_add_kernel, x, hop_length=hop_length, axis=axis,
              op_name="overlap_add")


def _stft_kernel(x, window, n_fft, hop_length, center, pad_mode, normalized,
                 onesided):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frames_tl(x, n_fft, hop_length)  # (..., frames, n_fft)
    if window is not None:
        frames = frames * window
    spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(
        frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    # paddle layout: (..., n_freq, num_frames)
    return jnp.swapaxes(spec, -1, -2)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = None
    if window is not None:
        wv = window._value if isinstance(window, Tensor) else jnp.asarray(window)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            wv = jnp.pad(wv, (lp, n_fft - win_length - lp))
    if wv is not None:
        return op(lambda xv, w: _stft_kernel(xv, w, n_fft, hop_length, center,
                                             pad_mode, normalized, onesided),
                  x, Tensor(wv, _internal=True), op_name="stft")
    return op(lambda xv: _stft_kernel(xv, None, n_fft, hop_length, center,
                                      pad_mode, normalized, onesided),
              x, op_name="stft")


def _istft_kernel(spec, window, n_fft, hop_length, center, normalized,
                  onesided, length):
    # spec: (..., n_freq, num_frames) → (..., num_frames, n_freq)
    spec = jnp.swapaxes(spec, -1, -2)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
              else jnp.fft.ifft(spec, axis=-1).real)
    w = window if window is not None else jnp.ones((n_fft,), frames.dtype)
    sig = _overlap_add_tl(frames * w, hop_length)
    wsq = _overlap_add_tl(
        jnp.broadcast_to(w * w, frames.shape), hop_length)
    sig = sig / jnp.maximum(wsq, 1e-11)
    if center:
        sig = sig[..., n_fft // 2: sig.shape[-1] - n_fft // 2]
    if length is not None:
        sig = sig[..., :length]
    return sig


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = None
    if window is not None:
        wv = window._value if isinstance(window, Tensor) else jnp.asarray(window)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            wv = jnp.pad(wv, (lp, n_fft - win_length - lp))
    if wv is not None:
        return op(lambda xv, w: _istft_kernel(xv, w, n_fft, hop_length,
                                              center, normalized, onesided,
                                              length),
                  x, Tensor(wv, _internal=True), op_name="istft")
    return op(lambda xv: _istft_kernel(xv, None, n_fft, hop_length, center,
                                       normalized, onesided, length),
              x, op_name="istft")

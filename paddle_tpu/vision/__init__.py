"""paddle_tpu.vision — model zoo, datasets, transforms, detection ops.

Capability parity with python/paddle/vision/ of the reference.
"""
from . import datasets, detection, models, ops, transforms  # noqa: F401
from .models import *  # noqa: F401,F403
from .detection import (  # noqa: F401
    box_coder, box_iou, distribute_fpn_proposals, generate_proposals,
    multiclass_nms, prior_box,
)

_image_backend = "cv2"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image honoring the selected backend: 'pil' → PIL Image,
    'cv2' → HWC uint8 ndarray, 'tensor' → paddle Tensor."""
    import numpy as np

    backend = backend or _image_backend
    if str(path).endswith(".npy"):
        arr = np.load(path)
    else:
        from PIL import Image

        with Image.open(path) as im:
            if backend == "pil":
                return im.copy()
            arr = np.asarray(im)
    if backend == "cv2" and arr.ndim == 3 and arr.shape[2] == 3:
        arr = arr[..., ::-1]  # cv2 convention is BGR (Normalize(to_rgb=True)
        # then flips back, matching the reference pipeline)
    if backend == "tensor":
        from .transforms.functional import to_tensor

        return to_tensor(arr)
    return arr

"""Detection op suite.

Reference: paddle/fluid/operators/detection/ (~19k LoC CUDA/CPU:
box_coder_op, prior_box_op, multiclass_nms_op, distribute_fpn_proposals_op,
generate_proposals...). TPU-native split: dense per-box math (encode/decode,
prior generation, IoU) is jit-compatible jnp; selection ops with
data-dependent output sizes run host-side like the reference's CPU kernels.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..framework.autograd import call_op as op
from ..framework.tensor import Tensor

__all__ = ["box_coder", "prior_box", "multiclass_nms",
           "distribute_fpn_proposals", "box_iou", "generate_proposals"]


def _np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (detection/box_coder_op.cc)."""
    norm = 0.0 if box_normalized else 1.0

    def fn(pb, tb, *rest):
        pbv = rest[0] if rest else None
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
            if pbv is not None:
                out = out / pbv
            return out
        # decode_center_size: tb [N, 4] deltas (axis handling simplified to
        # the per-prior case the reference tests exercise)
        d = tb if pbv is None else tb * pbv
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)

    args = [prior_box, target_box]
    if prior_box_var is not None and not isinstance(prior_box_var,
                                                    (list, tuple)):
        args.append(prior_box_var)
    elif isinstance(prior_box_var, (list, tuple)):
        pv = Tensor(np.asarray(prior_box_var, np.float32))
        args.append(pv)
    return op(fn, *args, op_name="box_coder")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes over the feature map grid (detection/prior_box_op.cc).
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or img_h / H
    step_w = steps[0] or img_w / W

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []
    for ms in min_sizes:
        whs.append((ms, ms))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
    P = len(whs)

    cy, cx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    ccx = ((cx + offset) * step_w)[..., None]
    ccy = ((cy + offset) * step_h)[..., None]
    w = np.asarray([wh[0] for wh in whs])[None, None, :]
    h = np.asarray([wh[1] for wh in whs])[None, None, :]
    boxes = np.stack([(ccx - w / 2) / img_w, (ccy - h / 2) / img_h,
                      (ccx + w / 2) / img_w, (ccy + h / 2) / img_h], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          (H, W, P, 4)).copy()
    return (Tensor(boxes.astype(np.float32)), Tensor(var))


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] — dense, jit-compatible."""
    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter,
                                   1e-10)

    return op(fn, boxes1, boxes2, op_name="box_iou")


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=1000,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   name=None):
    """Per-class NMS over [N, 4] boxes x [C, N] scores
    (detection/multiclass_nms_op.cc, the v2 single-image form). Host-side:
    output count is data-dependent. Returns [M, 6] rows (label, score,
    x1, y1, x2, y2) (+ indices when return_index)."""
    boxes = _np(bboxes).astype(np.float64)
    scr = _np(scores).astype(np.float64)
    C = scr.shape[0]
    out, picked_idx = [], []
    for c in range(C):
        if c == background_label:
            continue
        s = scr[c]
        idx = np.where(s > score_threshold)[0]
        if idx.size == 0:
            continue
        order = idx[np.argsort(-s[idx], kind="stable")][:nms_top_k]
        keep = []
        suppressed = np.zeros(order.size, bool)
        b = boxes[order]
        norm = 0.0 if normalized else 1.0
        areas = np.maximum(b[:, 2] - b[:, 0] + norm, 0) * \
            np.maximum(b[:, 3] - b[:, 1] + norm, 0)
        thresh = nms_threshold
        for i in range(order.size):
            if suppressed[i]:
                continue
            keep.append(order[i])
            xx1 = np.maximum(b[i, 0], b[:, 0])
            yy1 = np.maximum(b[i, 1], b[:, 1])
            xx2 = np.minimum(b[i, 2], b[:, 2])
            yy2 = np.minimum(b[i, 3], b[:, 3])
            inter = np.maximum(xx2 - xx1 + norm, 0) * \
                np.maximum(yy2 - yy1 + norm, 0)
            iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
            suppressed |= iou > thresh
            if nms_eta < 1.0 and thresh > 0.5:
                thresh *= nms_eta
        for k in keep:
            out.append([c, scr[c, k], *boxes[k]])
            picked_idx.append(k)
    if out:
        arr = np.asarray(out, np.float32)
        order = np.argsort(-arr[:, 1], kind="stable")[:keep_top_k]
        arr = arr[order]
        picked = np.asarray(picked_idx, np.int64)[order]
    else:
        arr = np.zeros((0, 6), np.float32)
        picked = np.zeros((0,), np.int64)
    if return_index:
        return Tensor(arr), Tensor(picked)
    return Tensor(arr)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale
    (detection/distribute_fpn_proposals_op.cc):
    level = floor(refer_level + log2(sqrt(area) / refer_scale))."""
    rois = _np(fpn_rois).astype(np.float64)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    h = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, restore = [], np.empty(rois.shape[0], np.int64)
    pos = 0
    rois_num_per = []
    for l in range(min_level, max_level + 1):
        idx = np.where(lvl == l)[0]
        multi_rois.append(Tensor(rois[idx].astype(np.float32)))
        restore[idx] = np.arange(pos, pos + idx.size)
        pos += idx.size
        rois_num_per.append(Tensor(np.asarray([idx.size], np.int32)))
    out = [multi_rois, Tensor(restore[:, None])]
    if rois_num is not None:
        out.append(rois_num_per)
    return out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (detection/generate_proposals_v2_op.cc),
    single image: decode anchors with deltas, clip, filter small, NMS."""
    s = _np(scores).reshape(-1)
    d = _np(bbox_deltas).reshape(-1, 4)
    a = _np(anchors).reshape(-1, 4)
    v = _np(variances).reshape(-1, 4)
    H, W = float(_np(img_size).reshape(-1)[0]), float(
        _np(img_size).reshape(-1)[1])

    order = np.argsort(-s, kind="stable")[:pre_nms_top_n]
    s, d, a, v = s[order], d[order], a[order], v[order]
    aw = a[:, 2] - a[:, 0]
    ah = a[:, 3] - a[:, 1]
    acx = a[:, 0] + aw * 0.5
    acy = a[:, 1] + ah * 0.5
    cx = v[:, 0] * d[:, 0] * aw + acx
    cy = v[:, 1] * d[:, 1] * ah + acy
    w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
    h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
    boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, W)
    boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, H)
    keep = np.where((boxes[:, 2] - boxes[:, 0] >= min_size) &
                    (boxes[:, 3] - boxes[:, 1] >= min_size))[0]
    boxes, s = boxes[keep], s[keep]
    from .ops import nms as _nms

    k = np.asarray(_nms(Tensor(boxes.astype(np.float32)), nms_thresh,
                        Tensor(s.astype(np.float32))).numpy())[:post_nms_top_n]
    rois = Tensor(boxes[k].astype(np.float32))
    roi_scores = Tensor(s[k].astype(np.float32))
    if return_rois_num:
        return rois, roi_scores, Tensor(np.asarray([k.size], np.int32))
    return rois, roi_scores

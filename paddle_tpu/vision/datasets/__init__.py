"""Vision datasets (parity: python/paddle/vision/datasets/ — MNIST/
FashionMNIST/Cifar10/Cifar100/Flowers/VOC2012).

This environment has no network egress, so `download=True` raises with
instructions; all datasets parse the standard on-disk formats (IDX for MNIST,
pickled tar.gz batches for CIFAR) from user-supplied paths.
"""
from __future__ import annotations

import gzip
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "DatasetFolder", "ImageFolder", "VOC2012"]

_NO_DOWNLOAD = (
    "automatic download is unavailable in this environment; pass "
    "image_path/label_path (MNIST) or data_file (CIFAR) pointing at local "
    "copies of the standard dataset files")


def _open_maybe_gzip(path):
    with open(path, "rb") as f:
        magic = f.read(2)
    if magic == b"\x1f\x8b":
        return gzip.open(path, "rb")
    return open(path, "rb")


def _parse_idx(path):
    """Parse an IDX-format file (the MNIST container format)."""
    with _open_maybe_gzip(path) as f:
        data = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zeros != 0:
        raise ValueError(f"{path}: not an IDX file")
    dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16, 0x0C: np.int32,
             0x0D: np.float32, 0x0E: np.float64}[dtype_code]
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, dtype=np.dtype(dtype).newbyteorder(">"),
                        offset=4 + 4 * ndim)
    return arr.reshape(dims).astype(dtype)


class MNIST(Dataset):
    """MNIST from IDX files (parity: vision/datasets/mnist.py).

    Yields (image, label); image is float32 HW1 in [0,255] under
    backend='cv2' semantics (ndarray), label an int64 scalar ndarray.
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        if image_path is None or label_path is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        self.transform = transform
        self.backend = backend or "cv2"
        images = _parse_idx(image_path)
        labels = _parse_idx(label_path)
        assert len(images) == len(labels), "image/label count mismatch"
        self.images = images.reshape(len(images), 28, 28).astype("float32")
        self.labels = labels.reshape(-1, 1).astype("int64")

    def __getitem__(self, idx):
        image, label = self.images[idx], self.labels[idx]
        image = image[:, :, None]  # HWC
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _Cifar(Dataset):
    MODE_FLAG_MAP = {}
    META = {}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        self.transform = transform
        self.backend = backend or "cv2"
        self._load_data(data_file)

    def _load_data(self, data_file):
        filter_key = self.MODE_FLAG_MAP[self.mode]
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames() if filter_key in n]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                lab = batch.get(self.LABEL_KEY)
                images.append(np.asarray(data, dtype="float32"))
                labels.extend(lab)
        data = np.concatenate(images, axis=0)
        self.data = [(data[i], labels[i]) for i in range(len(labels))]

    def __getitem__(self, idx):
        image, label = self.data[idx]
        image = image.reshape(3, 32, 32).transpose(1, 2, 0)  # HWC
        if self.transform is not None:
            image = self.transform(image)
        return image, np.array(label, dtype="int64")

    def __len__(self):
        return len(self.data)


class Cifar10(_Cifar):
    MODE_FLAG_MAP = {"train": "data_batch", "test": "test_batch"}
    LABEL_KEY = b"labels"


class Cifar100(_Cifar):
    MODE_FLAG_MAP = {"train": "train", "test": "test"}
    LABEL_KEY = b"fine_labels"


class Flowers(Dataset):
    """Flowers-102. Requires local copies of the image tarball + labels."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        raise NotImplementedError(
            "Flowers requires scipy .mat label files and image tarballs; "
            "use DatasetFolder over an extracted copy instead (" +
            _NO_DOWNLOAD + ")")


class DatasetFolder(Dataset):
    """Generic folder-of-class-folders dataset (vision/datasets/folder.py)."""

    IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        extensions = extensions or self.IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(extensions))
                if ok:
                    samples.append((path, self.class_to_idx[c]))
        if not samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.samples = samples
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            with Image.open(path) as im:
                return np.asarray(im.convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL unavailable; use .npy images or pass a "
                               "custom loader") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.array(target, dtype="int64")

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Flat folder of images, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        extensions = extensions or self.IMG_EXTENSIONS
        samples = []
        for dirpath, _, fnames in sorted(os.walk(root)):
            for fname in sorted(fnames):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(extensions))
                if ok:
                    samples.append(path)
        if not samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.samples = samples
        self.loader = loader or self._default_loader

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (vision/datasets/voc2012.py): the
    VOCdevkit directory with JPEGImages/, SegmentationClass/ and
    ImageSets/Segmentation/{train,val,trainval}.txt. Yields
    (image CHW uint8, label HW uint8)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        import os

        root = data_file
        if os.path.isdir(os.path.join(root, "VOC2012")):
            root = os.path.join(root, "VOC2012")
        lst = os.path.join(root, "ImageSets", "Segmentation",
                           f"{mode.lower()}.txt")
        with open(lst) as f:
            names = [ln.strip() for ln in f if ln.strip()]
        self._images = [os.path.join(root, "JPEGImages", f"{n}.jpg")
                        for n in names]
        self._labels = [os.path.join(root, "SegmentationClass", f"{n}.png")
                        for n in names]
        self.transform = transform

    def __getitem__(self, idx):
        import numpy as np
        from PIL import Image

        img = np.asarray(Image.open(self._images[idx]).convert("RGB"))
        lbl = np.asarray(Image.open(self._labels[idx]))
        img = img.transpose(2, 0, 1)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self._images)

"""InceptionV3 (parity: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["InceptionV3", "inception_v3"]


class ConvBNLayer(nn.Layer):
    def __init__(self, in_ch, out_ch, filter_size, stride=1, padding=0,
                 groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, filter_size, stride=stride,
                              padding=padding, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InceptionStem(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv_1a_3x3 = ConvBNLayer(3, 32, 3, stride=2)
        self.conv_2a_3x3 = ConvBNLayer(32, 32, 3)
        self.conv_2b_3x3 = ConvBNLayer(32, 64, 3, padding=1)
        self.max_pool = nn.MaxPool2D(kernel_size=3, stride=2)
        self.conv_3b_1x1 = ConvBNLayer(64, 80, 1)
        self.conv_4a_3x3 = ConvBNLayer(80, 192, 3)

    def forward(self, x):
        x = self.conv_2b_3x3(self.conv_2a_3x3(self.conv_1a_3x3(x)))
        x = self.max_pool(x)
        x = self.conv_4a_3x3(self.conv_3b_1x1(x))
        return self.max_pool(x)


class InceptionA(nn.Layer):
    def __init__(self, num_channels, pool_features):
        super().__init__()
        self.branch1x1 = ConvBNLayer(num_channels, 64, 1)
        self.branch5x5_1 = ConvBNLayer(num_channels, 48, 1)
        self.branch5x5_2 = ConvBNLayer(48, 64, 5, padding=2)
        self.branch3x3dbl_1 = ConvBNLayer(num_channels, 64, 1)
        self.branch3x3dbl_2 = ConvBNLayer(64, 96, 3, padding=1)
        self.branch3x3dbl_3 = ConvBNLayer(96, 96, 3, padding=1)
        self.branch_pool = nn.AvgPool2D(kernel_size=3, stride=1, padding=1,
                                        exclusive=False)
        self.branch_pool_conv = ConvBNLayer(num_channels, pool_features, 1)

    def forward(self, x):
        b1 = self.branch1x1(x)
        b5 = self.branch5x5_2(self.branch5x5_1(x))
        b3 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = self.branch_pool_conv(self.branch_pool(x))
        return concat([b1, b5, b3, bp], axis=1)


class InceptionB(nn.Layer):
    """Grid-size reduction 35→17."""

    def __init__(self, num_channels):
        super().__init__()
        self.branch3x3 = ConvBNLayer(num_channels, 384, 3, stride=2)
        self.branch3x3dbl_1 = ConvBNLayer(num_channels, 64, 1)
        self.branch3x3dbl_2 = ConvBNLayer(64, 96, 3, padding=1)
        self.branch3x3dbl_3 = ConvBNLayer(96, 96, 3, stride=2)
        self.branch_pool = nn.MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([
            self.branch3x3(x),
            self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
            self.branch_pool(x),
        ], axis=1)


class InceptionC(nn.Layer):
    def __init__(self, num_channels, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = ConvBNLayer(num_channels, 192, 1)
        self.branch7x7_1 = ConvBNLayer(num_channels, c7, 1)
        self.branch7x7_2 = ConvBNLayer(c7, c7, (1, 7), padding=(0, 3))
        self.branch7x7_3 = ConvBNLayer(c7, 192, (7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = ConvBNLayer(num_channels, c7, 1)
        self.branch7x7dbl_2 = ConvBNLayer(c7, c7, (7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = ConvBNLayer(c7, c7, (1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = ConvBNLayer(c7, c7, (7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = ConvBNLayer(c7, 192, (1, 7), padding=(0, 3))
        self.branch_pool = nn.AvgPool2D(kernel_size=3, stride=1, padding=1,
                                        exclusive=False)
        self.branch_pool_conv = ConvBNLayer(num_channels, 192, 1)

    def forward(self, x):
        b1 = self.branch1x1(x)
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        b7d = self.branch7x7dbl_5(self.branch7x7dbl_4(self.branch7x7dbl_3(
            self.branch7x7dbl_2(self.branch7x7dbl_1(x)))))
        bp = self.branch_pool_conv(self.branch_pool(x))
        return concat([b1, b7, b7d, bp], axis=1)


class InceptionD(nn.Layer):
    """Grid-size reduction 17→8."""

    def __init__(self, num_channels):
        super().__init__()
        self.branch3x3_1 = ConvBNLayer(num_channels, 192, 1)
        self.branch3x3_2 = ConvBNLayer(192, 320, 3, stride=2)
        self.branch7x7x3_1 = ConvBNLayer(num_channels, 192, 1)
        self.branch7x7x3_2 = ConvBNLayer(192, 192, (1, 7), padding=(0, 3))
        self.branch7x7x3_3 = ConvBNLayer(192, 192, (7, 1), padding=(3, 0))
        self.branch7x7x3_4 = ConvBNLayer(192, 192, 3, stride=2)
        self.branch_pool = nn.MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([
            self.branch3x3_2(self.branch3x3_1(x)),
            self.branch7x7x3_4(self.branch7x7x3_3(self.branch7x7x3_2(
                self.branch7x7x3_1(x)))),
            self.branch_pool(x),
        ], axis=1)


class InceptionE(nn.Layer):
    def __init__(self, num_channels):
        super().__init__()
        self.branch1x1 = ConvBNLayer(num_channels, 320, 1)
        self.branch3x3_1 = ConvBNLayer(num_channels, 384, 1)
        self.branch3x3_2a = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = ConvBNLayer(num_channels, 448, 1)
        self.branch3x3dbl_2 = ConvBNLayer(448, 384, 3, padding=1)
        self.branch3x3dbl_3a = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = nn.AvgPool2D(kernel_size=3, stride=1, padding=1,
                                        exclusive=False)
        self.branch_pool_conv = ConvBNLayer(num_channels, 192, 1)

    def forward(self, x):
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=1)
        b3d = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        b3d = concat([self.branch3x3dbl_3a(b3d), self.branch3x3dbl_3b(b3d)],
                     axis=1)
        bp = self.branch_pool_conv(self.branch_pool(x))
        return concat([b1, b3, b3d, bp], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inception_stem = InceptionStem()
        self.inception_block_list = nn.LayerList([
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048),
        ])
        if with_pool:
            self.avg_pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(p=0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.inception_stem(x)
        for block in self.inception_block_list:
            x = block(x)
        if self.with_pool:
            x = self.avg_pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x).flatten(1))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return InceptionV3(**kwargs)

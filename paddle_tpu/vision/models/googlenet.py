"""GoogLeNet / Inception-v1 (parity: python/paddle/vision/models/googlenet.py).

Like the reference, `forward` returns (main, aux1, aux2) logits in train mode.
"""
from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["GoogLeNet", "googlenet"]


class ConvLayer(nn.Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 groups=1):
        super().__init__()
        self._conv = nn.Conv2D(num_channels, num_filters, filter_size,
                               stride=stride, padding=(filter_size - 1) // 2,
                               groups=groups, bias_attr=False)
        self._relu = nn.ReLU()

    def forward(self, x):
        return self._relu(self._conv(x))


class Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self._conv1 = ConvLayer(in_ch, c1, 1)
        self._conv3r = ConvLayer(in_ch, c3r, 1)
        self._conv3 = ConvLayer(c3r, c3, 3)
        self._conv5r = ConvLayer(in_ch, c5r, 1)
        self._conv5 = ConvLayer(c5r, c5, 5)
        self._pool = nn.MaxPool2D(kernel_size=3, stride=1, padding=1)
        self._convprj = ConvLayer(in_ch, proj, 1)

    def forward(self, x):
        return concat([
            self._conv1(x),
            self._conv3(self._conv3r(x)),
            self._conv5(self._conv5r(x)),
            self._convprj(self._pool(x)),
        ], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self._conv = ConvLayer(3, 64, 7, 2)
        self._pool = nn.MaxPool2D(kernel_size=3, stride=2)
        self._conv_1 = ConvLayer(64, 64, 1)
        self._conv_2 = ConvLayer(64, 192, 3)

        self._ince3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self._ince3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self._ince4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self._ince4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self._ince4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self._ince4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self._ince4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self._ince5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self._ince5b = Inception(832, 384, 192, 384, 48, 128, 128)

        if with_pool:
            self._pool_5 = nn.AdaptiveAvgPool2D(1)

        if num_classes > 0:
            # aux-head pools belong to the classifier, not the global pool
            self._pool_o1 = nn.AvgPool2D(kernel_size=5, stride=3)
            self._pool_o2 = nn.AvgPool2D(kernel_size=5, stride=3)
            self._drop = nn.Dropout(p=0.4)
            self._fc_out = nn.Linear(1024, num_classes)
            # aux head 1
            self._conv_o1 = ConvLayer(512, 128, 1)
            self._fc_o1 = nn.Linear(1152, 1024)
            self._drop_o1 = nn.Dropout(p=0.7)
            self._out1 = nn.Linear(1024, num_classes)
            # aux head 2
            self._conv_o2 = ConvLayer(528, 128, 1)
            self._fc_o2 = nn.Linear(1152, 1024)
            self._drop_o2 = nn.Dropout(p=0.7)
            self._out2 = nn.Linear(1024, num_classes)
        self._relu = nn.ReLU()

    def forward(self, inputs):
        x = self._pool(self._conv(inputs))
        x = self._pool(self._conv_2(self._conv_1(x)))
        x = self._pool(self._ince3b(self._ince3a(x)))
        ince4a = self._ince4a(x)
        ince4d = self._ince4d(self._ince4c(self._ince4b(ince4a)))
        x = self._pool(self._ince4e(ince4d))
        x = self._ince5b(self._ince5a(x))

        if self.with_pool:
            x = self._pool_5(x)
        if self.num_classes > 0:
            main = self._fc_out(self._drop(x).flatten(1))
            o1 = self._pool_o1(ince4a)
            o1 = self._relu(self._fc_o1(self._conv_o1(o1).flatten(1)))
            out1 = self._out1(self._drop_o1(o1))
            o2 = self._pool_o2(ince4d)
            o2 = self._relu(self._fc_o2(self._conv_o2(o2).flatten(1)))
            out2 = self._out2(self._drop_o2(o2))
            return main, out1, out2
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return GoogLeNet(**kwargs)

"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn
from ...tensor import concat, reshape, split, transpose

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self._conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                               groups=groups, bias_attr=False)
        self._batch_norm = nn.BatchNorm2D(out_c)
        self._act = _act(act) if act else None

    def forward(self, x):
        x = self._batch_norm(self._conv(x))
        return self._act(x) if self._act is not None else x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        branch_c = out_c // 2
        self._conv_pw = ConvBNLayer(in_c // 2, branch_c, 1, act=act)
        self._conv_dw = ConvBNLayer(branch_c, branch_c, 3, stride=stride,
                                    padding=1, groups=branch_c, act=None)
        self._conv_linear = ConvBNLayer(branch_c, branch_c, 1, act=act)

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        x2 = self._conv_linear(self._conv_dw(self._conv_pw(x2)))
        out = concat([x1, x2], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(nn.Layer):
    """Downsampling unit: both branches convolve, stride 2."""

    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        branch_c = out_c // 2
        self._conv_dw_1 = ConvBNLayer(in_c, in_c, 3, stride=stride, padding=1,
                                      groups=in_c, act=None)
        self._conv_linear_1 = ConvBNLayer(in_c, branch_c, 1, act=act)
        self._conv_pw_2 = ConvBNLayer(in_c, branch_c, 1, act=act)
        self._conv_dw_2 = ConvBNLayer(branch_c, branch_c, 3, stride=stride,
                                      padding=1, groups=branch_c, act=None)
        self._conv_linear_2 = ConvBNLayer(branch_c, branch_c, 1, act=act)

    def forward(self, x):
        x1 = self._conv_linear_1(self._conv_dw_1(x))
        x2 = self._conv_linear_2(self._conv_dw_2(self._conv_pw_2(x)))
        return channel_shuffle(concat([x1, x2], axis=1), 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        stage_out = {
            0.25: [-1, 24, 24, 48, 96, 512],
            0.33: [-1, 24, 32, 64, 128, 512],
            0.5: [-1, 24, 48, 96, 192, 1024],
            1.0: [-1, 24, 116, 232, 464, 1024],
            1.5: [-1, 24, 176, 352, 704, 1024],
            2.0: [-1, 24, 244, 488, 976, 2048],
        }[scale]

        self._conv1 = ConvBNLayer(3, stage_out[1], 3, stride=2, padding=1,
                                  act=act)
        self._max_pool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        blocks = []
        for stage_id, num_repeat in enumerate(stage_repeats):
            for i in range(num_repeat):
                if i == 0:
                    blocks.append(InvertedResidualDS(
                        stage_out[stage_id + 1], stage_out[stage_id + 2], 2,
                        act))
                else:
                    blocks.append(InvertedResidual(
                        stage_out[stage_id + 2], stage_out[stage_id + 2], 1,
                        act))
        self._block_list = nn.LayerList(blocks)
        self._last_conv = ConvBNLayer(stage_out[-2], stage_out[-1], 1, act=act)
        if with_pool:
            self._pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self._out_c = stage_out[-1]
            self._fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self._max_pool(self._conv1(x))
        for block in self._block_list:
            x = block(x)
        x = self._last_conv(x)
        if self.with_pool:
            x = self._pool2d_avg(x)
        if self.num_classes > 0:
            x = self._fc(x.flatten(1))
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)

"""DenseNet (parity: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class BNACConvLayer(nn.Layer):
    """BN → ReLU → Conv, the pre-activation unit DenseNet composes."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 pad=0, groups=1):
        super().__init__()
        self._batch_norm = nn.BatchNorm2D(num_channels)
        self._relu = nn.ReLU()
        self._conv = nn.Conv2D(num_channels, num_filters, filter_size,
                               stride=stride, padding=pad, groups=groups,
                               bias_attr=False)

    def forward(self, x):
        return self._conv(self._relu(self._batch_norm(x)))


class DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.dropout = dropout
        self.bn_ac_func1 = BNACConvLayer(num_channels, bn_size * growth_rate, 1)
        self.bn_ac_func2 = BNACConvLayer(bn_size * growth_rate, growth_rate, 3,
                                         pad=1)
        if dropout:
            self.dropout_func = nn.Dropout(p=dropout)

    def forward(self, x):
        conv = self.bn_ac_func1(x)
        conv = self.bn_ac_func2(conv)
        if self.dropout:
            conv = self.dropout_func(conv)
        return concat([x, conv], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, num_channels, num_layers, bn_size, growth_rate, dropout):
        super().__init__()
        layers = []
        ch = num_channels
        for _ in range(num_layers):
            layers.append(DenseLayer(ch, growth_rate, bn_size, dropout))
            ch += growth_rate
        self.dense_layers = nn.LayerList(layers)
        self.out_channels = ch

    def forward(self, x):
        for layer in self.dense_layers:
            x = layer(x)
        return x


class TransitionLayer(nn.Layer):
    def __init__(self, num_channels, num_output_features):
        super().__init__()
        self.conv_ac_func = BNACConvLayer(num_channels, num_output_features, 1)
        self.pool2d_avg = nn.AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool2d_avg(self.conv_ac_func(x))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        supported = {
            121: (64, 32, [6, 12, 24, 16]),
            161: (96, 48, [6, 12, 36, 24]),
            169: (64, 32, [6, 12, 32, 32]),
            201: (64, 32, [6, 12, 48, 32]),
            264: (64, 32, [6, 12, 64, 48]),
        }
        assert layers in supported, f"supported layers {sorted(supported)}"
        num_init_features, growth_rate, block_config = supported[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1_func = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features),
            nn.ReLU(),
        )
        self.pool2d_max = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)

        blocks, transitions = [], []
        ch = num_init_features
        for i, num_layers in enumerate(block_config):
            block = DenseBlock(ch, num_layers, bn_size, growth_rate, dropout)
            blocks.append(block)
            ch = block.out_channels
            if i != len(block_config) - 1:
                transitions.append(TransitionLayer(ch, ch // 2))
                ch = ch // 2
        self.dense_blocks = nn.LayerList(blocks)
        self.transitions = nn.LayerList(transitions)
        self.batch_norm = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.out = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.pool2d_max(self.conv1_func(x))
        for i, block in enumerate(self.dense_blocks):
            x = block(x)
            if i < len(self.transitions):
                x = self.transitions[i](x)
        x = self.relu(self.batch_norm(x))
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.out(x.flatten(1))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)

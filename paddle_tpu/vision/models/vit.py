"""Vision Transformer.

Reference precedent: paddle.vision ships the CNN zoo; ViT lives in
PaddleClas on the same nn.TransformerEncoder this port already provides —
included here because the patch-embed + encoder shape is THE natural TPU
model (pure matmuls on the MXU, no im2col).
"""
from __future__ import annotations

import numpy as np

from ... import tensor as ops
from ...framework.tensor import Tensor
from ...nn import functional as F
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.conv import Conv2D
from ...nn.layer.layers import Layer
from ...nn.layer.norm import LayerNorm
from ...nn.layer.transformer import (
    TransformerEncoder, TransformerEncoderLayer,
)

__all__ = ["VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16"]


class PatchEmbed(Layer):
    """Conv-as-patchify: a stride=patch conv IS the patch projection (XLA
    lowers it to one matmul over unfolded patches)."""

    def __init__(self, img_size, patch_size, embed_dim, in_channels=3):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_channels, embed_dim, kernel_size=patch_size,
                           stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                      # [b, D, H/p, W/p]
        b, d = x.shape[0], x.shape[1]
        x = ops.reshape(x, [b, d, -1])
        return ops.transpose(x, [0, 2, 1])    # [b, N, D]


class VisionTransformer(Layer):
    def __init__(self, img_size=224, patch_size=16, embed_dim=768, depth=12,
                 num_heads=12, mlp_ratio=4.0, num_classes=1000, dropout=0.0,
                 in_channels=3):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, embed_dim,
                                      in_channels)
        n = self.patch_embed.num_patches
        rs = np.random.RandomState(0)
        self.cls_token = self.create_parameter(shape=[1, 1, embed_dim])
        self.cls_token.set_value(np.zeros((1, 1, embed_dim), np.float32))
        self.pos_embed = self.create_parameter(shape=[1, n + 1, embed_dim])
        self.pos_embed.set_value(
            (rs.randn(1, n + 1, embed_dim) * 0.02).astype(np.float32))
        self.dropout = Dropout(dropout)
        enc_layer = TransformerEncoderLayer(
            embed_dim, num_heads, int(embed_dim * mlp_ratio),
            dropout=dropout, activation="gelu", normalize_before=True)
        self.encoder = TransformerEncoder(enc_layer, depth)
        self.norm = LayerNorm(embed_dim)
        self.head = Linear(embed_dim, num_classes) if num_classes else None

    def forward(self, x):
        x = self.patch_embed(x)
        b = x.shape[0]
        cls = ops.expand(self.cls_token, [b, 1, self.cls_token.shape[-1]])
        x = ops.concat([cls, x], axis=1) + self.pos_embed
        x = self.dropout(x)
        x = self.encoder(x)
        x = self.norm(x)
        cls_out = x[:, 0]
        return self.head(cls_out) if self.head is not None else cls_out


def vit_b_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=768, depth=12,
                             num_heads=12, **kwargs)


def vit_b_32(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=32, embed_dim=768, depth=12,
                             num_heads=12, **kwargs)


def vit_l_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=1024, depth=24,
                             num_heads=16, **kwargs)

"""MobileNetV1 (parity: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNLayer(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, num_groups=1):
        super().__init__()
        self._conv = nn.Conv2D(in_channels, out_channels, kernel_size,
                               stride=stride, padding=padding,
                               groups=num_groups, bias_attr=False)
        self._norm_layer = nn.BatchNorm2D(out_channels)
        self._act = nn.ReLU()

    def forward(self, x):
        return self._act(self._norm_layer(self._conv(x)))


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self._depthwise_conv = ConvBNLayer(
            in_channels, int(out_channels1 * scale), kernel_size=3,
            stride=stride, padding=1, num_groups=int(num_groups * scale))
        self._pointwise_conv = ConvBNLayer(
            int(out_channels1 * scale), int(out_channels2 * scale),
            kernel_size=1, stride=1, padding=0)

    def forward(self, x):
        return self._pointwise_conv(self._depthwise_conv(x))


class MobileNetV1(nn.Layer):
    """MobileNetV1: depthwise-separable conv stack; depthwise convs lower to
    XLA grouped convolutions (feature_group_count)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        # (in, c1, c2, groups, stride)
        cfg = [
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2), (128, 128, 128, 128, 1),
            (128, 128, 256, 128, 2), (256, 256, 256, 256, 1),
            (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 1024, 512, 2), (1024, 1024, 1024, 1024, 1),
        ]
        blocks = []
        for in_c, c1, c2, g, s in cfg:
            blocks.append(DepthwiseSeparable(
                int(in_c * scale), c1, c2, g, s, scale))
        self.dwsl = nn.LayerList(blocks)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        for dws in self.dwsl:
            x = dws(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return MobileNetV1(scale=scale, **kwargs)

"""ResNet family (ResNet / Wide-ResNet / ResNeXt).

Capability parity with the reference's ResNet zoo
(python/paddle/vision/models/resnet.py:155,352 — ResNet class + resnet18/34/50/
101/152, wide_resnet50_2/101_2 constructors). Built new on paddle_tpu.nn; the
NCHW conv stack lowers to XLA convolutions that tile onto the TPU MXU.
"""
from __future__ import annotations

from ... import nn

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
    "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d",
    "wide_resnet50_2", "wide_resnet101_2",
]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        if dilation > 1:
            raise NotImplementedError("dilation > 1 not supported in BasicBlock")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet model from "Deep Residual Learning for Image Recognition".

    Args:
        block: BasicBlock or BottleneckBlock.
        depth: 18/34/50/101/152.
        width: base width of each block group (64 for classic resnets).
        num_classes: classifier size; <=0 drops the fc head.
        with_pool: keep the global average pool.
        groups: cardinality (ResNeXt).
    """

    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {
            18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
            101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
        }
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                               padding=3, bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        previous_dilation = self.dilation
        if dilate:
            self.dilation *= stride
            stride = 1
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, previous_dilation, norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups, base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(arch, Block, depth, pretrained, **kwargs):
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled with paddle_tpu (no model hub "
            "in this environment); load a converted state_dict via "
            "model.set_state_dict instead")
    return ResNet(Block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet("resnet18", BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet("resnet34", BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet("resnet50", BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet("resnet101", BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet("resnet152", BottleneckBlock, 152, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext50_32x4d", BottleneckBlock, 50, pretrained, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext50_64x4d", BottleneckBlock, 50, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext101_32x4d", BottleneckBlock, 101, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext101_64x4d", BottleneckBlock, 101, pretrained, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext152_32x4d", BottleneckBlock, 152, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext152_64x4d", BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 64 * 2
    return _resnet("wide_resnet50_2", BottleneckBlock, 50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 64 * 2
    return _resnet("wide_resnet101_2", BottleneckBlock, 101, pretrained, **kwargs)


class ResNeXt(ResNet):
    """Aggregated residual transformations (reference:
    vision/models/resnext.py ResNeXt): a ResNet of BottleneckBlocks with
    grouped 3x3 convolutions — depth picks the layout, cardinality the
    group count."""

    def __init__(self, depth=50, cardinality=32, num_classes=1000,
                 with_pool=True):
        super().__init__(BottleneckBlock, depth=depth, width=4,
                         num_classes=num_classes, with_pool=with_pool,
                         groups=cardinality)

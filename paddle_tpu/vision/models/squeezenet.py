"""SqueezeNet (parity: python/paddle/vision/models/squeezenet.py)."""
from __future__ import annotations

from ... import nn
from ...tensor import concat

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class MakeFire(nn.Layer):
    def __init__(self, in_channels, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self._conv = nn.Conv2D(in_channels, squeeze_channels, 1)
        self._conv_path1 = nn.Conv2D(squeeze_channels, expand1x1_channels, 1)
        self._conv_path2 = nn.Conv2D(squeeze_channels, expand3x3_channels, 3,
                                     padding=1)
        self._relu = nn.ReLU()

    def forward(self, x):
        x = self._relu(self._conv(x))
        x1 = self._relu(self._conv_path1(x))
        x2 = self._relu(self._conv_path2(x))
        return concat([x1, x2], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool

        if version == "1.0":
            self._conv = nn.Conv2D(3, 96, 7, stride=2)
            fires = [
                MakeFire(96, 16, 64, 64), MakeFire(128, 16, 64, 64),
                MakeFire(128, 32, 128, 128), MakeFire(256, 32, 128, 128),
                MakeFire(256, 48, 192, 192), MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256), MakeFire(512, 64, 256, 256),
            ]
            self._pool_marks = {2, 6}  # maxpool after fire3 and fire7
        elif version == "1.1":
            self._conv = nn.Conv2D(3, 64, 3, stride=2, padding=1)
            fires = [
                MakeFire(64, 16, 64, 64), MakeFire(128, 16, 64, 64),
                MakeFire(128, 32, 128, 128), MakeFire(256, 32, 128, 128),
                MakeFire(256, 48, 192, 192), MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256), MakeFire(512, 64, 256, 256),
            ]
            self._pool_marks = {1, 3}  # maxpool after fire2 and fire4
        else:
            raise ValueError(f"unsupported SqueezeNet version {version!r}")
        self._fires = nn.LayerList(fires)
        self._relu = nn.ReLU()
        self._pool = nn.MaxPool2D(3, 2)
        if num_classes > 0:
            self._drop = nn.Dropout(0.5)
            self._conv_last = nn.Conv2D(512, num_classes, 1)
        if with_pool:
            self._avg_pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self._pool(self._relu(self._conv(x)))
        for i, fire in enumerate(self._fires):
            x = fire(x)
            if i in self._pool_marks:
                x = self._pool(x)
        if self.num_classes > 0:
            x = self._relu(self._conv_last(self._drop(x)))
        if self.with_pool:
            x = self._avg_pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights not bundled; use set_state_dict")
    return SqueezeNet(version="1.1", **kwargs)

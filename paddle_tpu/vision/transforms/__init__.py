from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, center_crop, crop, hflip,
    normalize, pad, resize, rotate, to_grayscale, to_tensor, vflip,
)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomErasing, RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose,
)

"""Functional image transforms on numpy HWC arrays (PIL optional).

Parity: python/paddle/vision/transforms/functional.py (+ functional_cv2.py).
Host-side preprocessing stays on CPU/NumPy by design — the TPU sees only the
batched, normalized tensors produced by the DataLoader.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "resize", "pad", "crop", "center_crop", "hflip", "vflip",
    "adjust_brightness", "adjust_contrast", "adjust_hue", "normalize",
    "rotate", "to_grayscale",
]


def _as_np(img):
    if hasattr(img, "mode"):  # PIL image
        return np.asarray(img)
    return np.asarray(img)


def _ensure_hwc(img):
    img = _as_np(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    """uint8 HWC image → float32 tensor scaled to [0, 1]."""
    from ...framework.tensor import Tensor

    img = _ensure_hwc(pic)
    if img.dtype == np.uint8:
        img = img.astype("float32") / 255.0
    else:
        img = img.astype("float32")
    if data_format == "CHW":
        img = np.transpose(img, (2, 0, 1))
    return Tensor(img)


def _interp_resize(img, h, w, interpolation="bilinear"):
    """Pure-NumPy separable resize (nearest / bilinear)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    dtype = img.dtype
    if interpolation == "nearest":
        rows = np.clip((np.arange(h) + 0.5) * ih / h, 0, ih - 1).astype(int)
        cols = np.clip((np.arange(w) + 0.5) * iw / w, 0, iw - 1).astype(int)
        return img[rows][:, cols]
    # bilinear with half-pixel centers
    fy = (np.arange(h) + 0.5) * ih / h - 0.5
    fx = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(fy).astype(int), 0, ih - 1)
    x0 = np.clip(np.floor(fx).astype(int), 0, iw - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(fy - y0, 0, 1)[:, None, None]
    wx = np.clip(fx - x0, 0, 1)[None, :, None]
    im = img.astype("float32")
    if im.ndim == 2:
        im = im[:, :, None]
        squeeze = True
    else:
        squeeze = False
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if squeeze:
        out = out[:, :, 0]
    if np.issubdtype(dtype, np.integer):
        out = np.clip(np.round(out), np.iinfo(dtype).min,
                      np.iinfo(dtype).max)
    return out.astype(dtype)


def resize(img, size, interpolation="bilinear"):
    img = _as_np(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if w <= h:
            ow = size
            oh = int(size * h / w)
        else:
            oh = size
            ow = int(size * w / h)
        return _interp_resize(img, oh, ow, interpolation)
    return _interp_resize(img, size[0], size[1], interpolation)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _ensure_hwc(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    pads = [(top, bottom), (left, right), (0, 0)]
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, pads, mode=mode)


def crop(img, top, left, height, width):
    img = _as_np(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


def adjust_brightness(img, brightness_factor):
    img = _as_np(img)
    out = img.astype("float32") * brightness_factor
    if img.dtype == np.uint8:
        return np.clip(out, 0, 255).astype("uint8")
    return out


def adjust_contrast(img, contrast_factor):
    img = _as_np(img)
    im = img.astype("float32")
    mean = im.mean()
    out = (im - mean) * contrast_factor + mean
    if img.dtype == np.uint8:
        return np.clip(out, 0, 255).astype("uint8")
    return out


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor in [-0.5, 0.5] (RGB in/out)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = _ensure_hwc(img)
    if img.shape[2] < 3:
        return img  # hue rotation is the identity on grayscale
    dtype = img.dtype
    im = img.astype("float32") / (255.0 if dtype == np.uint8 else 1.0)
    r, g, b = im[..., 0], im[..., 1], im[..., 2]
    maxc = im[..., :3].max(-1)
    minc = im[..., :3].min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0)
    dn = np.maximum(d, 1e-12)
    h = np.select(
        [maxc == r, maxc == g],
        [((g - b) / dn) % 6.0, (b - r) / dn + 2.0],
        default=(r - g) / dn + 4.0,
    ) / 6.0
    h = np.where(d == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    options = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1),
    ], 0)  # (6, H, W, 3)
    idx = np.broadcast_to(i[None, :, :, None], (1,) + i.shape + (3,))
    out = np.take_along_axis(options, idx, axis=0)[0]
    if dtype == np.uint8:
        return np.clip(out * 255.0, 0, 255).astype("uint8")
    return out.astype(dtype)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, dtype="float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    if to_rgb:
        img = img[::-1] if data_format == "CHW" else img[..., ::-1]
    return (img - mean) / std


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by `angle` degrees counter-clockwise (inverse-map sampling)."""
    img = _ensure_hwc(img)
    h, w = img.shape[:2]
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    if expand:
        nw = int(abs(w * cos) + abs(h * sin) + 0.5)
        nh = int(abs(w * sin) + abs(h * cos) + 0.5)
    else:
        nw, nh = w, h
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    if center is not None:
        cx, cy = center
    ncy, ncx = (nh - 1) / 2.0, (nw - 1) / 2.0
    ys, xs = np.mgrid[0:nh, 0:nw]
    # inverse rotation: output (x,y) ← input coords
    xi = (xs - ncx) * cos - (ys - ncy) * sin + cx
    yi = (xs - ncx) * sin + (ys - ncy) * cos + cy
    if interpolation == "bilinear":
        x0 = np.floor(xi).astype(int)
        y0 = np.floor(yi).astype(int)
        fx = (xi - x0)[..., None]
        fy = (yi - y0)[..., None]
        acc = np.zeros((nh, nw, img.shape[2]), dtype="float32")
        wsum = np.zeros((nh, nw, 1), dtype="float32")
        for dy, dx, wgt in ((0, 0, (1 - fy) * (1 - fx)), (0, 1, (1 - fy) * fx),
                            (1, 0, fy * (1 - fx)), (1, 1, fy * fx)):
            yc, xc = y0 + dy, x0 + dx
            ok = (yc >= 0) & (yc < h) & (xc >= 0) & (xc < w)
            yc2 = np.clip(yc, 0, h - 1)
            xc2 = np.clip(xc, 0, w - 1)
            m = ok[..., None].astype("float32") * wgt
            acc += img[yc2, xc2].astype("float32") * m
            wsum += m
        fill_arr = np.asarray(fill, dtype="float32")  # scalar or per-channel
        out = np.where(wsum > 0, acc / np.maximum(wsum, 1e-12), fill_arr)
        if np.issubdtype(img.dtype, np.integer):
            out = np.clip(np.round(out), np.iinfo(img.dtype).min,
                          np.iinfo(img.dtype).max)
        return out.astype(img.dtype)
    xi_r = np.round(xi).astype(int)
    yi_r = np.round(yi).astype(int)
    valid = (xi_r >= 0) & (xi_r < w) & (yi_r >= 0) & (yi_r < h)
    out = np.full((nh, nw, img.shape[2]), fill, dtype=img.dtype)
    out[valid] = img[yi_r[valid], xi_r[valid]]
    return out


def to_grayscale(img, num_output_channels=1):
    img = _ensure_hwc(img)
    if img.shape[2] == 1:
        if num_output_channels == 3:
            return np.repeat(img, 3, axis=2)
        return img
    w = np.array([0.299, 0.587, 0.114], dtype="float32")
    gray = (img[..., :3].astype("float32") @ w)
    if img.dtype == np.uint8:
        gray = np.clip(np.round(gray), 0, 255).astype("uint8")
    gray = gray[:, :, None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=2)
    return gray

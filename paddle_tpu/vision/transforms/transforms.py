"""Transform classes (parity: python/paddle/vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose",
    "Normalize", "BrightnessTransform", "ContrastTransform", "HueTransform",
    "SaturationTransform", "ColorJitter", "RandomCrop", "Pad",
    "RandomRotation", "Grayscale", "RandomErasing",
]


class BaseTransform:
    """Base class: applies `_apply_image` to the input (single-key pipeline)."""

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            # (image, *rest): transform the image leg only
            return (self._apply_image(inputs[0]),) + inputs[1:]
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _get_param(self, img):
        h, w = np.asarray(img).shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(random.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return top, left, ch, cw
        # fallback to center crop
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            cw, ch = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            ch, cw = h, int(round(h * self.ratio[1]))
        else:
            cw, ch = w, h
        return (h - ch) // 2, (w - cw) // 2, ch, cw

    def _apply_image(self, img):
        top, left, ch, cw = self._get_param(img)
        img = F.crop(img, top, left, ch, cw)
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, self.order)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format,
                           self.to_rgb)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = F.to_grayscale(img, num_output_channels=3)
        out = (np.asarray(img, dtype="float32") * factor
               + gray.astype("float32") * (1 - factor))
        if np.asarray(img).dtype == np.uint8:
            return np.clip(out, 0, 255).astype("uint8")
        return out


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        h, w = np.asarray(img).shape[:2]
        th, tw = self.size
        if self.pad_if_needed and w < tw:
            img = F.pad(img, (tw - w, 0), self.fill, self.padding_mode)
        if self.pad_if_needed and h < th:
            img = F.pad(img, (0, th - h), self.fill, self.padding_mode)
        h, w = np.asarray(img).shape[:2]
        if h == th and w == tw:
            return img
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be positive if scalar")
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        was_tensor = hasattr(img, "_value")  # paddle Tensor (post-ToTensor)
        img = np.array(img)  # dense copy; CHW tensors or HWC arrays both fine
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] > 4
        h, w = (img.shape[1], img.shape[2]) if chw else img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                if chw:
                    img[:, top:top + eh, left:left + ew] = self.value
                else:
                    img[top:top + eh, left:left + ew] = self.value
                break
        if was_tensor:
            from ...framework.tensor import Tensor

            return Tensor(img)
        return img

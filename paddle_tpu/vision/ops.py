"""Vision ops (parity: python/paddle/vision/ops.py + operators/detection/).

roi_align / roi_pool / psroi_pool, yolo_box decode, nms, deform_conv2d.
Each op is a pure jax-traceable kernel dispatched through the framework's
functional-kernel path (`call_op`), so it fuses under jit; the matmul
contraction in deform_conv2d rides the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.autograd import call_op as op
from ..framework.tensor import Tensor
from .. import nn

__all__ = ["roi_align", "roi_pool", "psroi_pool", "prroi_pool", "yolo_box",
           "nms", "deform_conv2d", "DeformConv2D", "RoIAlign", "RoIPool",
           "PrRoIPool"]


def _bilinear_sample(feat, ys, xs, boundary="zero"):
    """feat (C,H,W); ys/xs arbitrary same-shaped float grids → (C, *grid).

    boundary="zero": out-of-range corners contribute 0 (deformable-conv
    semantics, matches zero-padded convolution).
    boundary="clamp": coordinates clamp into the image and only samples
    farther than one pixel outside are zeroed (RoIAlign semantics).
    """
    C, H, W = feat.shape
    if boundary == "clamp":
        valid = ((ys >= -1.0) & (ys <= H) & (xs >= -1.0) & (xs <= W))
        ys = jnp.clip(ys, 0.0, H - 1.0)
        xs = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = ys - y0
    wx1 = xs - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def gather(yi, xi):
        yi_c = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
        xi_c = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
        vals = feat[:, yi_c, xi_c]  # (C, *grid)
        if boundary == "zero":
            ok = ((yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1))
            vals = vals * ok.astype(feat.dtype)
        return vals

    out = (gather(y0, x0) * (wy0 * wx0) + gather(y0, x1) * (wy0 * wx1)
           + gather(y1, x0) * (wy1 * wx0) + gather(y1, x1) * (wy1 * wx1))
    if boundary == "clamp":
        out = out * valid.astype(feat.dtype)
    return out


def _roi_batch_index(boxes_num, n_rois):
    counts = jnp.asarray(boxes_num, jnp.int32)
    return jnp.repeat(jnp.arange(counts.shape[0]), counts,
                      total_repeat_length=n_rois)


def _roi_align_kernel(x, boxes, boxes_num, output_size, spatial_scale,
                      sampling_ratio, aligned):
    ph, pw = output_size
    ratio = sampling_ratio if sampling_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0
    batch_idx = _roi_batch_index(boxes_num, boxes.shape[0])

    def one_roi(box, b_idx):
        feat = x[b_idx]
        x1 = box[0] * spatial_scale - offset
        y1 = box[1] * spatial_scale - offset
        x2 = box[2] * spatial_scale - offset
        y2 = box[3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        sub_y = (jnp.arange(ratio) + 0.5) / ratio
        sub_x = (jnp.arange(ratio) + 0.5) / ratio
        sy = y1 + (jnp.arange(ph)[:, None] + sub_y[None, :]) * bin_h
        sx = x1 + (jnp.arange(pw)[:, None] + sub_x[None, :]) * bin_w
        ys = jnp.broadcast_to(sy[:, None, :, None], (ph, pw, ratio, ratio))
        xs = jnp.broadcast_to(sx[None, :, None, :], (ph, pw, ratio, ratio))
        vals = _bilinear_sample(feat, ys, xs, "clamp")  # (C, ph, pw, r, r)
        return vals.mean(axis=(-1, -2))

    return jax.vmap(one_roi)(boxes, batch_idx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference: operators/roi_align_op.*): average of bilinear
    samples over each output bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return op(_roi_align_kernel, x, boxes, boxes_num,
              output_size=tuple(output_size), spatial_scale=spatial_scale,
              sampling_ratio=sampling_ratio, aligned=aligned,
              op_name="roi_align")


def _roi_pool_kernel(x, boxes, boxes_num, output_size, spatial_scale):
    ph, pw = output_size
    ratio = 4
    batch_idx = _roi_batch_index(boxes_num, boxes.shape[0])

    def one_roi(box, b_idx):
        feat = x[b_idx]
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        sub = (jnp.arange(ratio) + 0.5) / ratio
        sy = y1 + (jnp.arange(ph)[:, None] + sub[None, :]) * bin_h
        sx = x1 + (jnp.arange(pw)[:, None] + sub[None, :]) * bin_w
        ys = jnp.broadcast_to(sy[:, None, :, None], (ph, pw, ratio, ratio))
        xs = jnp.broadcast_to(sx[None, :, None, :], (ph, pw, ratio, ratio))
        vals = _bilinear_sample(feat, ys, xs, "clamp")
        return vals.max(axis=(-1, -2))

    return jax.vmap(one_roi)(boxes, batch_idx)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (reference: operators/roi_pool_op.*): max over quantized bins,
    approximated on a fixed sampling grid (TPU-friendly static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return op(_roi_pool_kernel, x, boxes, boxes_num,
              output_size=tuple(output_size), spatial_scale=spatial_scale,
              op_name="roi_pool")


def _psroi_pool_kernel(x, boxes, boxes_num, output_size, spatial_scale):
    ph, pw = output_size
    N, C, H, W = x.shape
    out_c = C // (ph * pw)
    ratio = 2
    batch_idx = _roi_batch_index(boxes_num, boxes.shape[0])

    def one_roi(box, b_idx):
        # channel group (i,j) is sampled only at its own output bin
        feat = x[b_idx].reshape(out_c, ph, pw, H, W)
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        rh = jnp.maximum(box[3] * spatial_scale - y1, 1.0)
        rw = jnp.maximum(box[2] * spatial_scale - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        sub = (jnp.arange(ratio) + 0.5) / ratio
        sy = y1 + (jnp.arange(ph)[:, None] + sub[None, :]) * bin_h  # (ph, r)
        sx = x1 + (jnp.arange(pw)[:, None] + sub[None, :]) * bin_w  # (pw, r)
        ys = jnp.broadcast_to(sy[:, None, :, None], (ph, pw, ratio, ratio))
        xs = jnp.broadcast_to(sx[None, :, None, :], (ph, pw, ratio, ratio))
        feat_bins = feat.transpose(1, 2, 0, 3, 4).reshape(ph * pw, out_c, H, W)

        def sample_bin(feat_bin, ys_bin, xs_bin):
            return _bilinear_sample(feat_bin, ys_bin, xs_bin,
                                    "clamp").mean((-1, -2))

        vals = jax.vmap(sample_bin)(feat_bins,
                                    ys.reshape(ph * pw, ratio, ratio),
                                    xs.reshape(ph * pw, ratio, ratio))
        return vals.reshape(ph, pw, out_c).transpose(2, 0, 1)

    return jax.vmap(one_roi)(boxes, batch_idx)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (operators/detection/psroi_pool_op.*):
    channel group (i,j) feeds output bin (i,j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return op(_psroi_pool_kernel, x, boxes, boxes_num,
              output_size=tuple(output_size), spatial_scale=spatial_scale,
              op_name="psroi_pool")


def _yolo_box_kernel(x, img_size, anchors, class_num, conf_thresh,
                     downsample_ratio, clip_bbox, scale_x_y, iou_aware,
                     iou_aware_factor):
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    anchors_arr = jnp.asarray(anchors, jnp.float32).reshape(an_num, 2)
    if iou_aware:
        # layout: [an_num ioup channels, an_num*(5+class_num) pred channels]
        ioup = jax.nn.sigmoid(x[:, :an_num])  # (n, an_num, h, w)
        x = x[:, an_num:]
    pred = x.reshape(n, an_num, 5 + class_num, h, w)

    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bias = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - bias + grid_x) / w
    cy = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - bias + grid_y) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(pred[:, :, 2]) * anchors_arr[None, :, 0, None, None] / input_w
    bh = jnp.exp(pred[:, :, 3]) * anchors_arr[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(pred[:, :, 4])
    if iou_aware:
        conf = (ioup ** iou_aware_factor) * (conf ** (1.0 - iou_aware_factor))
    probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]

    im_h = jnp.asarray(img_size, jnp.float32)[:, 0][:, None, None, None]
    im_w = jnp.asarray(img_size, jnp.float32)[:, 1][:, None, None, None]
    x1 = (cx - bw / 2) * im_w
    y1 = (cy - bh / 2) * im_h
    x2 = (cx + bw / 2) * im_w
    y2 = (cy + bh / 2) * im_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, im_w - 1)
        y1 = jnp.clip(y1, 0, im_h - 1)
        x2 = jnp.clip(x2, 0, im_w - 1)
        y2 = jnp.clip(y2, 0, im_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf > conf_thresh).reshape(n, -1, 1)
    return boxes * mask, scores * mask.astype(scores.dtype)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head output into boxes+scores
    (reference: operators/detection/yolo_box_op.*)."""
    return op(_yolo_box_kernel, x, img_size, anchors=tuple(anchors),
              class_num=class_num, conf_thresh=conf_thresh,
              downsample_ratio=downsample_ratio, clip_bbox=clip_bbox,
              scale_x_y=scale_x_y, iou_aware=iou_aware,
              iou_aware_factor=iou_aware_factor, op_name="yolo_box")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy hard-NMS. Data-dependent output size ⇒ runs on host NumPy
    (same stance as the reference's CPU kernel, operators/detection/)."""
    boxes_np = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    scores_np = None
    if scores is not None:
        scores_np = np.asarray(
            scores.numpy() if isinstance(scores, Tensor) else scores)
    if category_idxs is not None:
        cats = np.asarray(category_idxs.numpy()
                          if isinstance(category_idxs, Tensor)
                          else category_idxs)
        keep_all = []
        for c in (categories if categories is not None else np.unique(cats)):
            idx = np.where(cats == c)[0]
            sub = nms(boxes_np[idx], iou_threshold,
                      None if scores_np is None else scores_np[idx])
            keep_all.extend(idx[np.asarray(sub.numpy(), dtype=int)])
        keep_all = np.asarray(keep_all, dtype="int64")
        if scores_np is not None:
            keep_all = keep_all[np.argsort(-scores_np[keep_all],
                                           kind="stable")]
        if top_k is not None:
            keep_all = keep_all[:top_k]
        return Tensor(keep_all)

    n = len(boxes_np)
    order = (np.arange(n) if scores_np is None
             else np.argsort(-scores_np, kind="stable"))
    x1, y1, x2, y2 = boxes_np.T
    areas = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    keep = []
    suppressed = np.zeros(n, dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(x1[i], x1)
        yy1 = np.maximum(y1[i], y1)
        xx2 = np.minimum(x2[i], x2)
        yy2 = np.minimum(y2[i], y2)
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
    keep = np.asarray(keep, dtype="int64")
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def _deform_conv2d_kernel(x, offset, weight, bias, mask, stride, padding,
                          dilation, deformable_groups, groups):
    sh, sw = stride
    ph_, pw_ = padding
    dh, dw = dilation
    N, C, H, W = x.shape
    out_c, in_c_per_g, kh, kw = weight.shape
    out_h = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1

    base_y = (jnp.arange(out_h) * sh - ph_)[None, :, None]
    base_x = (jnp.arange(out_w) * sw - pw_)[None, None, :]
    ky = jnp.repeat(jnp.arange(kh) * dh, kw).reshape(-1)[:, None, None]
    kx = jnp.tile(jnp.arange(kw) * dw, kh).reshape(-1)[:, None, None]
    grid_y = (base_y + ky).astype(jnp.float32)  # (kh*kw, out_h, out_w)
    grid_x = (base_x + kx).astype(jnp.float32)

    off = offset.reshape(N, deformable_groups, kh * kw, 2, out_h, out_w)
    m = (mask.reshape(N, deformable_groups, kh * kw, out_h, out_w)
         if mask is not None else
         jnp.ones((N, deformable_groups, kh * kw, out_h, out_w), x.dtype))
    cpg = C // deformable_groups

    def per_image(feat, off_n, m_n):
        def per_dg(feat_g, off_g, m_g):
            ys = grid_y + off_g[:, 0]
            xs = grid_x + off_g[:, 1]
            vals = _bilinear_sample(feat_g, ys, xs)  # (cpg, kh*kw, oh, ow)
            return vals * m_g[None]

        feat_r = feat.reshape(deformable_groups, cpg, H, W)
        vals = jax.vmap(per_dg)(feat_r, off_n, m_n)
        return vals.reshape(C, kh * kw, out_h, out_w)

    cols = jax.vmap(per_image)(x, off, m)
    cols = cols.reshape(N, groups, in_c_per_g * kh * kw, out_h * out_w)
    w = weight.reshape(groups, out_c // groups, in_c_per_g * kh * kw)
    out = jnp.einsum("ngkp,gok->ngop", cols, w)
    out = out.reshape(N, out_c, out_h, out_w)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: operators/deformable_conv_op.*).

    Gather-based: bilinear-sample the input at offset positions, then one big
    grouped matmul (MXU) against the flattened kernel.
    """
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    # call_op substitutes only Tensor positions; None passes through untouched
    return op(_deform_conv2d_kernel, x, offset, weight, bias, mask,
              stride=_pair(stride), padding=_pair(padding),
              dilation=_pair(dilation), deformable_groups=deformable_groups,
              groups=groups, op_name="deformable_conv")


class DeformConv2D(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *kernel_size],
            attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, self._stride, self._padding,
            self._dilation, self._deformable_groups, self._groups, mask)


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(nn.Layer):
    """Layer form of psroi_pool (reference: vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, *self._args)


def read_file(filename, name=None):
    """Read raw file bytes into a uint8 tensor (reference: vision/ops.py
    read_file over read_file_op)."""
    import numpy as np

    from ..framework.tensor import to_tensor

    with open(filename, "rb") as f:
        data = f.read()
    return to_tensor(np.frombuffer(data, dtype=np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference: vision/ops.py
    decode_jpeg over nvjpeg; here PIL on host — decode is an input-pipeline
    op, not a TPU kernel)."""
    import io

    import numpy as np

    from PIL import Image

    from ..framework.tensor import to_tensor

    raw = bytes(np.asarray(x.numpy() if hasattr(x, "numpy") else x,
                           dtype=np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 detection loss (reference: yolov3_loss_op.cc/.h).

    x: [N, M*(5+C), H, W] raw predictions for this scale (M = len(
    anchor_mask)); gt_box [N, B, 4] in normalized xywh; gt_label [N, B].
    Loss = box (xy BCE + wh L1) + objectness BCE (ignoring predictions
    whose best-gt IoU > ignore_thresh) + class BCE, summed per image and
    meaned over the batch — the reference op's reduction.
    """
    import jax
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    mask = list(anchor_mask)
    M = len(mask)
    C = int(class_num)
    anc = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)

    def fn(pred, gbox, glbl, *rest):
        gscore = rest[0] if gt_score is not None else None
        N, _, H, W = pred.shape
        p = pred.reshape(N, M, 5 + C, H, W)
        px, py = jax.nn.sigmoid(p[:, :, 0]), jax.nn.sigmoid(p[:, :, 1])
        pw, ph = p[:, :, 2], p[:, :, 3]
        pobj = p[:, :, 4]
        pcls = p[:, :, 5:]
        stride = float(downsample_ratio)
        img_size = jnp.asarray([W * stride, H * stride], jnp.float32)

        gx = gbox[..., 0] * W                    # [N, B] in grid units
        gy = gbox[..., 1] * H
        gw = gbox[..., 2]                        # normalized
        gh = gbox[..., 3]
        valid = (gw > 0) & (gh > 0)              # [N, B]
        gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)

        # responsible anchor: best wh-IoU among ALL anchors; only boxes
        # whose best anchor is in this scale's mask contribute
        wh = jnp.stack([gw * img_size[0], gh * img_size[1]], -1)  # pixels
        inter = jnp.minimum(wh[..., None, 0], anc[None, None, :, 0]) * \
            jnp.minimum(wh[..., None, 1], anc[None, None, :, 1])
        union = wh[..., 0:1] * wh[..., 1:2] + anc[None, None, :, 0] * \
            anc[None, None, :, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)   # [N, B]
        mask_arr = jnp.asarray(mask)
        in_scale = (best[..., None] == mask_arr[None, None, :])   # [N,B,M]
        slot = jnp.argmax(in_scale, -1)                           # [N, B]
        resp = valid & jnp.any(in_scale, -1)

        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(
            jnp.exp(-jnp.abs(z)))

        ni = jnp.arange(N)[:, None]
        sel = (ni, slot, gj, gi)
        tx, ty = gx - gi, gy - gj
        aw = anc[mask_arr][slot]                                  # [N,B,2]
        tw = jnp.log(jnp.maximum(wh[..., 0] / jnp.maximum(aw[..., 0], 1e-9),
                                 1e-9))
        th = jnp.log(jnp.maximum(wh[..., 1] / jnp.maximum(aw[..., 1], 1e-9),
                                 1e-9))
        box_scale = 2.0 - gw * gh
        w_resp = resp.astype(jnp.float32) * box_scale
        if gscore is not None:
            w_resp = w_resp * gscore
        loss_xy = w_resp * (bce(p[:, :, 0][sel], tx) +
                            bce(p[:, :, 1][sel], ty))
        loss_wh = w_resp * (jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th))

        # objectness: positives at responsible cells; negatives elsewhere
        obj_t = jnp.zeros((N, M, H, W))
        obj_t = obj_t.at[sel].max(resp.astype(jnp.float32))
        # ignore mask: predicted boxes overlapping any gt above thresh
        grid_x = (jnp.arange(W)[None, None, None, :] + px) / W
        grid_y = (jnp.arange(H)[None, None, :, None] + py) / H
        pw_n = jnp.exp(pw) * anc[mask_arr][None, :, None, None, 0] / \
            img_size[0]
        ph_n = jnp.exp(ph) * anc[mask_arr][None, :, None, None, 1] / \
            img_size[1]

        def iou_with_gt(bx, by, bw, bh, g):
            gx0 = g[..., 0][..., None, None, None]   # [N, B, 1, 1, 1]
            gy0 = g[..., 1][..., None, None, None]
            gw0 = g[..., 2][..., None, None, None]
            gh0 = g[..., 3][..., None, None, None]
            x1 = jnp.maximum(bx - bw / 2, gx0 - gw0 / 2)
            y1 = jnp.maximum(by - bh / 2, gy0 - gh0 / 2)
            x2 = jnp.minimum(bx + bw / 2, gx0 + gw0 / 2)
            y2 = jnp.minimum(by + bh / 2, gy0 + gh0 / 2)
            inter = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
            ua = bw * bh + gw0 * gh0 - inter
            return inter / jnp.maximum(ua, 1e-9)

        # [N, B, M, H, W] iou of each prediction vs each gt
        ious = iou_with_gt(grid_x[:, None], grid_y[:, None], pw_n[:, None],
                           ph_n[:, None],
                           jnp.where(valid[..., None], gbox, 0.0))
        best_iou = jnp.max(ious, axis=1)                          # [N,M,H,W]
        noobj = (obj_t == 0) & (best_iou < ignore_thresh)
        loss_obj = jnp.sum(bce(pobj, obj_t) *
                           (obj_t + noobj.astype(jnp.float32)),
                           axis=(1, 2, 3))

        smooth = 1.0 / max(C, 1) if use_label_smooth else 0.0
        cls_t = jax.nn.one_hot(glbl, C) * (1.0 - smooth) + smooth / max(C, 1)
        pc = pcls.transpose(0, 1, 3, 4, 2)[sel]                   # [N,B,C]
        loss_cls = resp.astype(jnp.float32)[..., None] * bce(pc, cls_t)

        per_img = (jnp.sum(loss_xy + loss_wh, -1) + loss_obj +
                   jnp.sum(loss_cls, (-2, -1)))
        return per_img

    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None else [])
    return call_op(fn, *args, op_name="yolo_loss")


def prroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Precise RoI pooling (reference: prroi_pool_op.cc, PrRoIPooling):
    each output bin is the EXACT integral average of the bilinearly
    interpolated feature surface over the bin — no sampling-point
    approximation, fully differentiable in the box coordinates too.

    Closed form: with f(x, y) = Σ_ij F[i, j]·hat(x-i)·hat(y-j), the bin
    integral separates into 1-D integrals of the hat basis, so
    bin = w_yᵀ F w_x / area with w the per-node hat integrals.
    x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2); boxes_num: rois per
    image. Output [R, C, ph, pw].
    """
    ph, pw = (output_size if isinstance(output_size, (list, tuple))
              else (output_size, output_size))

    def hat_integral(a, b, nodes):
        """∫_a^b max(0, 1-|t-i|) dt for every node i (vectorized); a<=b."""
        def F(t):
            # antiderivative of the hat centered at node i, evaluated
            # piecewise: rising on [i-1,i], falling on [i,i+1]
            u = jnp.clip(t - (nodes - 1.0), 0.0, 1.0)
            rise = 0.5 * u * u
            v = jnp.clip(t - nodes, 0.0, 1.0)
            fall = v - 0.5 * v * v
            return rise + fall

        return F(b) - F(a)

    def fn(feat, bxs, bnum):
        N, C, H, W = feat.shape
        R = bxs.shape[0]
        img_of_roi = _roi_batch_index(bnum, R)
        sb = bxs * spatial_scale
        x1, y1, x2, y2 = sb[:, 0], sb[:, 1], sb[:, 2], sb[:, 3]
        bw = jnp.maximum(x2 - x1, 1e-6) / pw
        bh = jnp.maximum(y2 - y1, 1e-6) / ph
        xs = jnp.arange(W, dtype=jnp.float32)
        ys = jnp.arange(H, dtype=jnp.float32)
        # separable bin weights: WX [R, pw, W], WY [R, ph, H]
        ax = x1[:, None] + jnp.arange(pw)[None, :] * bw[:, None]
        ay = y1[:, None] + jnp.arange(ph)[None, :] * bh[:, None]
        WX = hat_integral(ax[..., None], (ax + bw[:, None])[..., None], xs)
        WY = hat_integral(ay[..., None], (ay + bh[:, None])[..., None], ys)
        g = feat[img_of_roi]                              # [R, C, H, W]
        out = jnp.einsum("rih,rchw,rjw->rcij", WY, g, WX)
        return out / (bw * bh)[:, None, None, None]

    return op(fn, x, boxes, boxes_num, op_name="prroi_pool")


class PrRoIPool(nn.Layer):
    """Layer form (reference: incubate PrRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return prroi_pool(x, boxes, boxes_num, *self._args)

"""paddle.distribution — probability distributions.

Parity: python/paddle/distribution.py of the reference (Normal, Uniform,
Categorical + kl_divergence) widened to the later-API families (Beta,
Dirichlet, Bernoulli, Multinomial, ExponentialFamily) the docs promise.
Sampling threads the framework RNG (framework/random.py next_key), so
distributions compose with jit tracing like every other op.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as rng_mod
from ..framework.autograd import call_op as op
from ..framework.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Dirichlet", "Multinomial", "ExponentialFamily", "kl_divergence",
    "register_kl",
]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if isinstance(
        x, (int, float, list, tuple, np.ndarray)) else x


def _wrap(v):
    t = Tensor(v, _internal=True)
    t.stop_gradient = True
    return t


def _sample_key(seed=0):
    """Key for sample(shape, seed): seed==0 draws from the framework RNG
    stream; a nonzero seed is honored (reference API contract) — same seed,
    same draw — by deriving the key from the seed alone."""
    if seed:
        return jax.random.key(int(seed))
    return rng_mod.next_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..tensor import exp

        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    @staticmethod
    def _extend(shape):
        return tuple(int(s) for s in shape)


class Normal(Distribution):
    """Gaussian (reference: fluid/layers/distributions + paddle.distribution
    Normal)."""

    def __init__(self, loc, scale, name=None):
        # keep the user's Tensors so rsample gradients reach them
        self._loc_t = loc if isinstance(loc, Tensor) else None
        self._scale_t = scale if isinstance(scale, Tensor) else None
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=(), seed=0):
        shape = self._extend(shape) + self.batch_shape
        key = _sample_key(seed)
        eps = jax.random.normal(key, shape, jnp.result_type(self.loc))
        return _wrap(self.loc + self.scale * eps)

    def rsample(self, shape=()):
        # reparameterized: gradient flows through loc/scale Tensors
        shape = self._extend(shape) + self.batch_shape
        key = rng_mod.next_key()
        eps = jax.random.normal(key, shape, jnp.result_type(self.loc))
        loc_t = self._loc_t if self._loc_t is not None else _wrap(self.loc)
        scale_t = (self._scale_t if self._scale_t is not None
                   else _wrap(self.scale))
        return op(lambda l, s: l + s * eps, loc_t, scale_t,
                  op_name="normal_rsample")

    def log_prob(self, value):
        loc, scale = self.loc, self.scale
        return op(lambda v: -((v - loc) ** 2) / (2 * scale ** 2)
                  - jnp.log(scale) - 0.5 * math.log(2 * math.pi),
                  value if isinstance(value, Tensor) else _wrap(_val(value)),
                  op_name="normal_log_prob")

    def entropy(self):
        return _wrap(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def kl_divergence(self, other):
        assert isinstance(other, Normal)
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return _wrap(jnp.broadcast_to(
            0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)),
            self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = self._extend(shape) + self.batch_shape
        key = _sample_key(seed)
        u = jax.random.uniform(key, shape, jnp.result_type(self.low))
        return _wrap(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        low, high = self.low, self.high

        def k(v):
            inside = (v >= low) & (v < high)
            return jnp.where(inside, -jnp.log(high - low), -jnp.inf)

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="uniform_log_prob")

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low),
                                      self.batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("either logits or probs must be given")
        if logits is not None:
            self.logits = _val(logits)
            self._log_p = jax.nn.log_softmax(self.logits, -1)
        else:
            p = _val(probs)
            p = p / p.sum(-1, keepdims=True)
            self.logits = jnp.log(jnp.maximum(p, 1e-38))
            self._log_p = self.logits
        super().__init__(self._log_p.shape[:-1])

    @property
    def probs(self):
        return _wrap(jnp.exp(self._log_p))

    def sample(self, shape=(), seed=0):
        shape = self._extend(shape)
        key = _sample_key(seed)
        idx = jax.random.categorical(key, self._log_p,
                                     shape=shape + self.batch_shape)
        return _wrap(idx.astype(dtype_mod.convert_dtype('int64')))

    def log_prob(self, value):
        lp = self._log_p

        def k(v):
            return jnp.take_along_axis(
                jnp.broadcast_to(lp, v.shape + lp.shape[-1:]),
                v[..., None].astype(jnp.int32), -1)[..., 0]

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="categorical_log_prob")

    def entropy(self):
        p = jnp.exp(self._log_p)
        return _wrap(-(p * self._log_p).sum(-1))

    def kl_divergence(self, other):
        assert isinstance(other, Categorical)
        p = jnp.exp(self._log_p)
        return _wrap((p * (self._log_p - other._log_p)).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs_v = _val(probs)
            self.logits_v = jnp.log(self.probs_v) - jnp.log1p(-self.probs_v)
        else:
            self.logits_v = _val(logits)
            self.probs_v = jax.nn.sigmoid(self.logits_v)
        super().__init__(self.probs_v.shape)

    @property
    def mean(self):
        return _wrap(self.probs_v)

    @property
    def variance(self):
        return _wrap(self.probs_v * (1 - self.probs_v))

    def sample(self, shape=(), seed=0):
        shape = self._extend(shape) + self.batch_shape
        key = _sample_key(seed)
        return _wrap(jax.random.bernoulli(
            key, jnp.broadcast_to(self.probs_v, shape)).astype(jnp.float32))

    def log_prob(self, value):
        logits = self.logits_v

        def k(v):
            return v * jax.nn.log_sigmoid(logits) + (1 - v) * \
                jax.nn.log_sigmoid(-logits)

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="bernoulli_log_prob")

    def entropy(self):
        p = self.probs_v
        return _wrap(-(p * jnp.log(jnp.maximum(p, 1e-38))
                       + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-38))))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    def sample(self, shape=()):
        shape = self._extend(shape) + self.batch_shape
        key = rng_mod.next_key()
        return _wrap(jax.random.beta(key, self.alpha, self.beta, shape))

    def log_prob(self, value):
        a, b = self.alpha, self.beta

        def k(v):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.betaln(a, b)))

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="beta_log_prob")

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        return _wrap(jax.scipy.special.betaln(a, b)
                     - (a - 1) * dg(a) - (b - 1) * dg(b)
                     + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        c = self.concentration
        return _wrap(c / c.sum(-1, keepdims=True))

    def sample(self, shape=()):
        shape = self._extend(shape) + self.batch_shape
        key = rng_mod.next_key()
        return _wrap(jax.random.dirichlet(key, self.concentration, shape))

    def log_prob(self, value):
        c = self.concentration
        gammaln = jax.scipy.special.gammaln

        def k(v):
            return (((c - 1) * jnp.log(v)).sum(-1)
                    + gammaln(c.sum(-1)) - gammaln(c).sum(-1))

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="dirichlet_log_prob")


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _val(probs)
        self.probs_v = p / p.sum(-1, keepdims=True)
        super().__init__(self.probs_v.shape[:-1], self.probs_v.shape[-1:])

    def sample(self, shape=()):
        shape = self._extend(shape) + self.batch_shape
        key = rng_mod.next_key()
        logp = jnp.log(jnp.maximum(self.probs_v, 1e-38))
        draws = jax.random.categorical(
            key, logp, shape=(self.total_count,) + shape)
        k = self.probs_v.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return _wrap(counts)

    def log_prob(self, value):
        logp = jnp.log(jnp.maximum(self.probs_v, 1e-38))
        gammaln = jax.scipy.special.gammaln

        def k(v):
            return (gammaln(v.sum(-1) + 1) - gammaln(v + 1).sum(-1)
                    + (v * logp).sum(-1))

        return op(k, value if isinstance(value, Tensor)
                  else _wrap(_val(value)), op_name="multinomial_log_prob")


class ExponentialFamily(Distribution):
    """Base for exp-family distributions (Bregman-divergence entropy hook)."""


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _wrap(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    dg = jax.scipy.special.digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return _wrap(jax.scipy.special.betaln(a2, b2)
                 - jax.scipy.special.betaln(a1, b1)
                 + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                 + (a2 - a1 + b2 - b1) * dg(a1 + b1))

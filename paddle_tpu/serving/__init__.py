"""paddle_tpu.serving — continuous-batching inference serving runtime.

ROADMAP item 1: the repo trains at scale; this package makes it SERVE.
Layers (each its own module, composable without the others):

  scheduler.py   admission-controlled request queue (open-loop arrivals
                 get backpressure at submit; drained requests re-admit
                 at the head — zero lost)
  kv_cache.py    paged/blocked KV cache: fixed-size blocks + free list +
                 per-sequence block tables, at-rest int8/fp8 blockwise
                 quantization through grad_comm's codec seam
                 (``_block_kernel_ops`` — pallas kernels under
                 ``FLAGS_kernel_autotune`` on TPU); refcounted prefix
                 sharing (chain-hash index, copy-on-write, LRU over
                 refcount-0 blocks) + reserve/rollback scratch
  model.py       GPTForCausalLM -> jitted prefill/decode/extend split
                 with zero-copy parameter sharing across replicas;
                 ``truncated(n)`` derives a self-draft model
  sampler.py     batched jitted top-k/top-p/temperature sampling over
                 per-request counter-based RNG streams (greedy = the
                 temperature=0 fast path)
  engine.py      the continuous-batching step loop (batch re-formed
                 every step; no head-of-line blocking), prefix-cached
                 admission, and lossless speculative decoding
  replica.py     N replicas behind the queue with watchdog +
                 ReplicaGuard eviction and drain-and-re-admit

Observability: ``serve_requests_total{outcome=}``, ``serve_queue_depth``,
``serve_request_latency_ms`` (p50/p95/p99 via ``Histogram.quantile``),
``serve_batch_occupancy{replica=}``, ``serve_kv_blocks_in_use{replica=}``,
``serve_replica_evictions_total{reason=}``,
``serve_prefix_cache_{hit,miss}_tokens_total``,
``serve_spec_accepted_per_step{replica=}``, plus a ``/serving`` section
on the telemetry exposition endpoint while a ``ReplicaSet`` is running.

Bench: ``tools/serve_bench.py`` (open-loop QPS sweep vs the sequential
single-request baseline + KV codec bytes + a replica-kill chaos phase +
a Zipfian prefix-cache mix + a speculative-decode scenario)
-> ``artifacts/serve_bench.json``, gated by ``tools/bench_gate.py``.
"""
from .engine import ReplicaBootBudgetExceeded, ServingEngine
from .kv_cache import BlockTable, KVBlockPool, KVCacheOOM, KV_CODECS
from .model import GPTDecodeModel, bucket_pow2
from .replica import ReplicaSet, StandbyReplica
from .sampler import BatchSampler, SamplingParams, default_sampler
from .scheduler import OUTCOMES, RequestQueue, ServeRequest

__all__ = [
    "ServingEngine", "ReplicaBootBudgetExceeded", "KVBlockPool",
    "BlockTable", "KVCacheOOM",
    "KV_CODECS", "GPTDecodeModel", "bucket_pow2", "ReplicaSet",
    "StandbyReplica", "RequestQueue", "ServeRequest", "OUTCOMES",
    "BatchSampler", "SamplingParams", "default_sampler",
]

"""paddle_tpu.serving — continuous-batching inference serving runtime.

ROADMAP item 1: the repo trains at scale; this package makes it SERVE.
Layers (each its own module, composable without the others):

  scheduler.py   admission-controlled request queue (open-loop arrivals
                 get backpressure at submit; drained requests re-admit
                 at the head — zero lost)
  kv_cache.py    paged/blocked KV cache: fixed-size blocks + free list +
                 per-sequence block tables, at-rest int8/fp8 blockwise
                 quantization through grad_comm's codec seam
                 (``_block_kernel_ops`` — pallas kernels under
                 ``FLAGS_kernel_autotune`` on TPU)
  model.py       GPTForCausalLM -> jitted prefill/decode split with
                 zero-copy parameter sharing across replicas
  engine.py      the continuous-batching step loop (batch re-formed
                 every step; no head-of-line blocking)
  replica.py     N replicas behind the queue with watchdog +
                 ReplicaGuard eviction and drain-and-re-admit

Observability: ``serve_requests_total{outcome=}``, ``serve_queue_depth``,
``serve_request_latency_ms`` (p50/p95/p99 via ``Histogram.quantile``),
``serve_batch_occupancy{replica=}``, ``serve_kv_blocks_in_use{replica=}``,
``serve_replica_evictions_total{reason=}``, plus a ``/serving`` section
on the telemetry exposition endpoint while a ``ReplicaSet`` is running.

Bench: ``tools/serve_bench.py`` (open-loop QPS sweep vs the sequential
single-request baseline + KV codec bytes + a replica-kill chaos phase)
-> ``artifacts/serve_bench.json``, gated by ``tools/bench_gate.py``.
"""
from .engine import ServingEngine
from .kv_cache import BlockTable, KVBlockPool, KVCacheOOM, KV_CODECS
from .model import GPTDecodeModel, bucket_pow2
from .replica import ReplicaSet
from .scheduler import OUTCOMES, RequestQueue, ServeRequest

__all__ = [
    "ServingEngine", "KVBlockPool", "BlockTable", "KVCacheOOM",
    "KV_CODECS", "GPTDecodeModel", "bucket_pow2", "ReplicaSet",
    "RequestQueue", "ServeRequest", "OUTCOMES",
]

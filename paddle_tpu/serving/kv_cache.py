"""Paged/blocked KV cache for the continuous-batching serving runtime.

The decode-side analog of grad_comm's bucketed gradient store: KV state
lives in fixed-size *blocks* of ``block_tokens`` tokens allocated from a
shared pool with a free list, and each sequence owns a *block table*
(ordered block ids + token count) instead of a contiguous buffer — so a
finishing sequence returns its blocks immediately and a new admission
reuses them, with zero compaction (the paged-attention allocation model).

At-rest quantization reuses the PR-8 EQuARX blockwise codecs verbatim:
one fp32 abs-max scale per ``quant_block`` elements, encoded/decoded
through ``grad_comm._block_kernel_ops()`` — the same seam the collectives
ride, so the pallas codec kernels (ops/pallas/codec.py) apply under
``FLAGS_kernel_autotune`` on TPU targets and the pure-jnp pair stays the
reference everywhere else. Each appended token is quantized exactly once
(scales aligned to token boundaries: ``quant_block`` must divide the
per-token element count), so a token's at-rest bits never change after
the write — which makes an incrementally-maintained dequantized working
copy bit-identical to a fresh :meth:`KVBlockPool.gather` (the engine
relies on this; ``tests/test_serving.py`` pins it).

``append`` returns the *dequantized read-back* of what was stored, never
the input: attention must see exactly the at-rest bits, or the quantized
cache's accuracy story would be fiction.

Prefix cache (PR 16). Blocks additionally carry a refcount and an
optional set of *index keys* — chain hashes of the token prefix whose KV
the block's leading rows hold (``h_i = H(h_{i-1} || chunk_i)``, so a key
names the FULL path from token 0, not just the chunk). Admission walks a
prompt's chain through the index and, on hits, maps the matched blocks
into the new table read-only (``refcount += 1``; they become the table's
leading ``n_shared`` entries) so a shared prefix is prefilled exactly
once. Sharing is copy-on-write: the first ``append`` whose frontier
lands inside a shared block copies the matched rows' at-rest bits
(payload + scales — bit-identical, no re-quantization) into a block
reserved for that purpose at admission (``cow_spare``), so a sequence
appending past a shared prefix can never mutate bytes another sequence
reads, and never needs a block it didn't reserve. Freed blocks that
carry index keys retire to an LRU of refcount-0 *cached* blocks instead
of the free list; the allocator evicts from that LRU (dropping the keys)
only when the free list runs dry. ``free_blocks`` therefore counts free
AND cached blocks — both are allocatable — and ``blocks_in_use`` counts
only blocks some live table references.

Speculative decoding rides ``reserve``/``rollback``: ``reserve`` grows a
table past its admission reservation for draft-token scratch, and
``rollback`` unwinds rejected tokens, returning every block beyond
``max(base_blocks, blocks_needed(n_tokens))`` — the same no-leak
discipline the PR-14 drain path exercises, pinned under chaos eviction.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["KVBlockPool", "BlockTable", "KVCacheOOM", "KV_CODECS"]

KV_CODECS = ("fp32", "int8_block", "fp8_block")


class KVCacheOOM(RuntimeError):
    """The pool has no free block for a requested allocation."""


@dataclass
class BlockTable:
    """Per-sequence view into the pool: ordered block ids + token count.

    ``n_shared`` leading blocks are mapped read-only from the prefix
    cache (refcounted; ``append`` never writes them in place — COW).
    ``cow_spare`` is the block reserved at admission for that COW when
    the last shared block is only partially matched. ``base_blocks`` is
    the admission reservation size — ``rollback`` never shrinks the
    table below it (the never-OOM-mid-flight guarantee).
    """

    block_ids: List[int] = field(default_factory=list)
    n_tokens: int = 0
    n_shared: int = 0
    cow_spare: Optional[int] = None
    base_blocks: int = 0

    def capacity(self, block_tokens: int) -> int:
        return len(self.block_ids) * block_tokens


def _chain_key(prev: bytes, tokens: np.ndarray) -> bytes:
    """h_i = H(h_{i-1} || tokens): a key names the whole token path."""
    return hashlib.sha1(
        prev + np.ascontiguousarray(tokens, np.int32).tobytes()).digest()


class KVBlockPool:
    """Fixed-size KV block pool with a free list, refcounted prefix
    sharing, and blockwise codecs.

    One pool per serving replica. ``elems_per_token`` is the flattened
    per-token KV payload (layers x {k,v} x heads x head_dim); callers
    append/gather ``[tokens, elems_per_token]`` fp32 matrices and the
    pool handles block placement and the at-rest codec.
    """

    def __init__(self, n_blocks: int, block_tokens: int,
                 elems_per_token: int, codec: str = "fp32",
                 quant_block: Optional[int] = None):
        from ..distributed import grad_comm

        if codec not in KV_CODECS:
            raise ValueError(f"codec must be one of {KV_CODECS}, got {codec!r}")
        if codec == "fp8_block" and grad_comm._FP8_WIRE is None:
            raise ValueError("fp8_block needs jax float8_e4m3fn support")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.elems_per_token = int(elems_per_token)
        self.codec = codec
        if codec != "fp32":
            qb = int(quant_block or min(self.elems_per_token, 1024))
            if self.elems_per_token % qb:
                raise ValueError(
                    f"quant_block ({qb}) must divide elems_per_token "
                    f"({self.elems_per_token}) so every append stays "
                    f"scale-aligned (tokens quantize exactly once)")
            self.quant_block = qb
            self._scales_per_token = self.elems_per_token // qb
        else:
            self.quant_block = 0
            self._scales_per_token = 0
        shape = (self.n_blocks, self.block_tokens, self.elems_per_token)
        if codec == "fp32":
            self._payload = np.zeros(shape, np.float32)
            self._scales = None
        else:
            wire = np.int8 if codec == "int8_block" else grad_comm._FP8_WIRE
            self._payload = np.zeros(shape, wire)
            self._scales = np.zeros(
                (self.n_blocks,
                 self.block_tokens * self._scales_per_token), np.float32)
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        # prefix cache state: per-block refcounts, chain-hash index
        # (key -> (block, matched rows)), per-block registered keys, and
        # the LRU of refcount-0 blocks still holding indexed content
        self._ref: List[int] = [0] * self.n_blocks
        self._index: Dict[bytes, Tuple[int, int]] = {}
        self._block_keys: List[List[bytes]] = [[] for _ in range(self.n_blocks)]
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.prefix_evictions = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ allocator
    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: truly free plus cached (evictable LRU)."""
        return len(self._free) + len(self._lru)

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by at least one live table."""
        return self.n_blocks - self.free_blocks

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks retained only for prefix reuse."""
        return len(self._lru)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_tokens)

    def _take_block_locked(self) -> int:
        """A writable block: free list first, then evict the LRU cached
        block (its index keys drop — the cache trades history for room)."""
        if self._free:
            return self._free.pop()
        if self._lru:
            bi, _ = self._lru.popitem(last=False)
            for key in self._block_keys[bi]:
                if self._index.get(key, (None,))[0] == bi:
                    del self._index[key]
            self._block_keys[bi] = []
            self.prefix_evictions += 1
            return bi
        raise KVCacheOOM(
            f"no free or evictable block "
            f"(pool of {self.n_blocks} x {self.block_tokens} tokens)")

    def _release_locked(self, bi: int):
        self._ref[bi] -= 1
        if self._ref[bi] < 0:
            raise AssertionError(f"block {bi} refcount underflow")
        if self._ref[bi] == 0:
            if self._block_keys[bi]:
                self._lru[bi] = None
                self._lru.move_to_end(bi)
            else:
                self._free.append(bi)

    def _match_locked(self, prefix: np.ndarray
                      ) -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Walk ``prefix`` through the chain index. Returns (full-block
        ids, optional (block, rows) partial tail hit, matched tokens)."""
        bt = self.block_tokens
        full: List[int] = []
        h = b""
        t = 0
        while t + bt <= len(prefix):
            key = _chain_key(h, prefix[t:t + bt])
            ent = self._index.get(key)
            if ent is None:
                break
            full.append(ent[0])
            h = key
            t += bt
        partial = None
        rem = len(prefix) - t
        for length in range(min(rem, bt - 1), 0, -1):
            ent = self._index.get(_chain_key(h, prefix[t:t + length]))
            if ent is not None:
                partial = (ent[0], length)
                break
        matched = t + (partial[1] if partial else 0)
        return full, partial, matched

    def probe_prefix(self, prefix_tokens) -> int:
        """Longest cached-prefix match in tokens (no allocation)."""
        prefix = np.asarray(prefix_tokens, np.int32)
        with self._lock:
            return self._match_locked(prefix)[2]

    def alloc_table(self, n_tokens: int,
                    prefix_tokens=None) -> BlockTable:
        """Allocate blocks covering ``n_tokens`` tokens up front (the
        engine reserves a sequence's full context budget at admission so
        decode can never OOM mid-flight).

        With ``prefix_tokens`` (the prompt prefix eligible for reuse —
        the engine caps it at ``n_prompt - 1`` so at least one token is
        always prefilled for logits), matched cached blocks become the
        table's leading shared entries and ``table.n_tokens`` starts at
        the matched length; only ``blocks_needed - full_shared`` fresh
        blocks are drawn (shared blocks count once in the reservation),
        plus one COW spare when the last match is partial.
        """
        need = self.blocks_needed(n_tokens)
        with self._lock:
            full: List[int] = []
            partial = None
            matched = 0
            if prefix_tokens is not None and len(prefix_tokens):
                prefix = np.asarray(prefix_tokens, np.int32)
                full, partial, matched = self._match_locked(prefix)
            n_shared = len(full) + (1 if partial else 0)
            fresh = need - len(full) - (1 if partial else 0)
            spare = 1 if partial else 0
            shared_ids = full + ([partial[0]] if partial else [])
            in_lru_shared = sum(1 for bi in shared_ids if bi in self._lru)
            if fresh + spare > self.free_blocks - in_lru_shared:
                raise KVCacheOOM(
                    f"need {fresh + spare} blocks beyond {n_shared} shared, "
                    f"{self.free_blocks - in_lru_shared} allocatable "
                    f"(pool of {self.n_blocks} x {self.block_tokens} tokens)")
            for bi in shared_ids:
                self._ref[bi] += 1
                self._lru.pop(bi, None)
            ids = shared_ids + [self._take_block_locked()
                                for _ in range(fresh)]
            for bi in ids[n_shared:]:
                self._ref[bi] += 1
            spare_id = None
            if spare:
                spare_id = self._take_block_locked()
                self._ref[spare_id] += 1
        return BlockTable(block_ids=ids, n_tokens=matched,
                          n_shared=n_shared, cow_spare=spare_id,
                          base_blocks=len(ids))

    def free_table(self, table: BlockTable):
        with self._lock:
            for bi in table.block_ids:
                self._release_locked(bi)
            if table.cow_spare is not None:
                self._release_locked(table.cow_spare)
        table.block_ids = []
        table.n_tokens = 0
        table.n_shared = 0
        table.cow_spare = None

    # --------------------------------------------------------- prefix index
    def register_prefix(self, table: BlockTable, prompt_tokens):
        """Index ``table``'s blocks under the chain keys of
        ``prompt_tokens`` so later admissions can share them. Every
        complete ``block_tokens`` chunk gets its full-chain key, and
        every block additionally gets keys for each proper prefix of its
        chunk (partial-tail matches stop anywhere). Rows being indexed
        are already immutable: appends only ever write at the frontier,
        which sits at or past ``len(prompt_tokens)`` when the engine
        calls this. First writer wins on key collisions (identical
        content — the chain hash covers the whole path)."""
        tokens = np.asarray(prompt_tokens, np.int32)
        bt = self.block_tokens
        with self._lock:
            if table.n_tokens < len(tokens):
                raise ValueError("register_prefix before the prompt's KV "
                                 "was appended")
            h = b""
            for start in range(0, len(tokens), bt):
                chunk = tokens[start:start + bt]
                bi = table.block_ids[start // bt]
                for length in range(1, len(chunk) + 1):
                    key = _chain_key(h, chunk[:length])
                    if key not in self._index:
                        self._index[key] = (bi, length)
                        self._block_keys[bi].append(key)
                if len(chunk) < bt:
                    break
                h = _chain_key(h, chunk)

    # ------------------------------------------------- speculative scratch
    def reserve(self, table: BlockTable, extra_tokens: int):
        """Grow the table so ``n_tokens + extra_tokens`` fit — draft-token
        scratch beyond the admission reservation. No-op when capacity
        already covers it; raises :class:`KVCacheOOM` (table unchanged)
        when the pool cannot back the growth."""
        need = self.blocks_needed(table.n_tokens + int(extra_tokens))
        with self._lock:
            grow = need - len(table.block_ids)
            if grow <= 0:
                return
            if grow > self.free_blocks:
                raise KVCacheOOM(
                    f"reserve wants {grow} blocks, "
                    f"{self.free_blocks} allocatable")
            for _ in range(grow):
                bi = self._take_block_locked()
                self._ref[bi] += 1
                table.block_ids.append(bi)

    def rollback(self, table: BlockTable, n_tokens: int):
        """Unwind the last ``n_tokens`` appended tokens (rejected draft
        positions). Stale at-rest rows need no scrubbing — reads are
        bounded by ``table.n_tokens`` and the next append overwrites —
        but every block beyond ``max(base_blocks, blocks_needed)``
        returns to the pool immediately: reserve/rollback must never
        leak. ``rollback(table, 0)`` unwinds no tokens but still trims
        excess reserved blocks — the cancel path for an unused
        :meth:`reserve`."""
        n = int(n_tokens)
        if n < 0 or n > table.n_tokens:
            raise ValueError(f"rollback of {n} from {table.n_tokens} tokens")
        with self._lock:
            table.n_tokens -= n
            keep = max(table.base_blocks,
                       self.blocks_needed(table.n_tokens))
            while len(table.block_ids) > keep:
                self._release_locked(table.block_ids.pop())

    # ---------------------------------------------------------------- codec
    def _encode_chunk(self, chunk: np.ndarray):
        """fp32 [t, ept] -> (payload [t, ept] wire-dtype, scales or None,
        dequantized read-back [t, ept] fp32)."""
        from ..distributed import grad_comm

        if self.codec == "fp32":
            stored = np.ascontiguousarray(chunk, np.float32)
            return stored, None, stored
        flat = chunk.reshape(-1)
        qb = self.quant_block
        absmax = grad_comm.block_absmax(flat, qb)
        scales = grad_comm.block_scales(absmax, self.codec)
        enc, dec = grad_comm._block_kernel_ops()
        q = enc(flat, scales, qb, self.codec)
        deq = np.asarray(dec(q, scales, 1, np.float32, flat.size),
                         np.float32).reshape(chunk.shape)
        wire = self._payload.dtype
        payload = np.asarray(q, dtype=wire).reshape(chunk.shape)
        return payload, np.asarray(scales, np.float32), deq

    def _decode_rows(self, payload: np.ndarray, scales) -> np.ndarray:
        """wire [t, ept] (+scales) -> fp32 [t, ept]."""
        from ..distributed import grad_comm

        if self.codec == "fp32":
            return np.array(payload, np.float32)
        qb = self.quant_block
        carrier = (payload.astype(np.int32) if self.codec == "int8_block"
                   else payload.astype(np.float32))
        _enc, dec = grad_comm._block_kernel_ops()
        numel = payload.size
        out = dec(carrier.reshape(-1, qb), np.asarray(scales, np.float32),
                  1, np.float32, numel)
        return np.asarray(out, np.float32).reshape(payload.shape)

    def _cow_locked(self, table: BlockTable, idx: int, rows: int):
        """Copy-on-write of shared block ``table.block_ids[idx]``: move
        its first ``rows`` at-rest rows (payload + scales — the exact
        bits, no re-quantization) into the admission-reserved spare and
        swap it into the table. The shared original keeps its index
        entries and refcount with the other readers."""
        if idx != table.n_shared - 1:
            raise AssertionError(
                "COW frontier must be the last shared block "
                f"(idx {idx}, n_shared {table.n_shared})")
        old = table.block_ids[idx]
        if table.cow_spare is not None:
            new = table.cow_spare
            table.cow_spare = None
        else:  # defensive: reservation should always have provided one
            new = self._take_block_locked()
            self._ref[new] += 1
        if rows:
            self._payload[new, :rows] = self._payload[old, :rows]
            if self._scales is not None:
                spt = self._scales_per_token
                self._scales[new, :rows * spt] = \
                    self._scales[old, :rows * spt]
        table.block_ids[idx] = new
        table.n_shared = idx
        self._release_locked(old)

    # ------------------------------------------------------------------- io
    def append(self, table: BlockTable, kv: np.ndarray) -> np.ndarray:
        """Append ``kv`` [t, elems_per_token] fp32 rows to the sequence.
        Returns the dequantized at-rest read-back of the same rows (what
        attention must consume). The table must already hold enough
        blocks (``alloc_table``/``reserve`` reserved them). A frontier
        inside a shared block triggers copy-on-write first — shared
        bytes are never mutated."""
        kv = np.asarray(kv, np.float32)
        if kv.ndim != 2 or kv.shape[1] != self.elems_per_token:
            raise ValueError(
                f"append wants [t, {self.elems_per_token}], got {kv.shape}")
        t = kv.shape[0]
        if table.n_tokens + t > table.capacity(self.block_tokens):
            raise KVCacheOOM(
                f"table holds {table.capacity(self.block_tokens)} tokens, "
                f"append to {table.n_tokens + t} exceeds the reservation")
        out = np.empty_like(kv)
        done = 0
        with self._lock:
            while done < t:
                pos = table.n_tokens + done
                idx = pos // self.block_tokens
                off = pos % self.block_tokens
                if idx < table.n_shared:
                    self._cow_locked(table, idx, off)
                bi = table.block_ids[idx]
                take = min(t - done, self.block_tokens - off)
                chunk = kv[done:done + take]
                payload, scales, deq = self._encode_chunk(chunk)
                self._payload[bi, off:off + take] = payload
                if scales is not None:
                    spt = self._scales_per_token
                    self._scales[bi, off * spt:(off + take) * spt] = scales
                out[done:done + take] = deq
                done += take
            table.n_tokens += t
        return out

    def gather(self, table: BlockTable) -> np.ndarray:
        """Dequantize the sequence's full KV prefix -> fp32
        [n_tokens, elems_per_token]."""
        out = np.empty((table.n_tokens, self.elems_per_token), np.float32)
        with self._lock:
            done = 0
            for bi in table.block_ids:
                if done >= table.n_tokens:
                    break
                take = min(self.block_tokens, table.n_tokens - done)
                scales = (None if self._scales is None else
                          self._scales[bi, :take * self._scales_per_token])
                out[done:done + take] = self._decode_rows(
                    self._payload[bi, :take], scales)
                done += take
        return out

    # ----------------------------------------------------------- accounting
    def block_bytes(self) -> int:
        """At-rest bytes of ONE block: payload + its scale slice."""
        b = self.block_tokens * self.elems_per_token * \
            self._payload.dtype.itemsize
        if self._scales is not None:
            b += self.block_tokens * self._scales_per_token * 4
        return b

    def bytes_in_use(self) -> int:
        """At-rest bytes of every allocated block (allocation granularity —
        what the pool actually holds, reservation included)."""
        return self.blocks_in_use * self.block_bytes()

    def fp32_equiv_bytes(self) -> int:
        """What the same allocation would hold un-quantized."""
        return (self.blocks_in_use * self.block_tokens *
                self.elems_per_token * 4)

    def stats(self) -> dict:
        return {
            "codec": self.codec,
            "n_blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "blocks_in_use": self.blocks_in_use,
            "free_blocks": self.free_blocks,
            "cached_blocks": self.cached_blocks,
            "prefix_evictions": self.prefix_evictions,
            "bytes_in_use": self.bytes_in_use(),
            "fp32_equiv_bytes": self.fp32_equiv_bytes(),
        }

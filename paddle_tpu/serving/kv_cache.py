"""Paged/blocked KV cache for the continuous-batching serving runtime.

The decode-side analog of grad_comm's bucketed gradient store: KV state
lives in fixed-size *blocks* of ``block_tokens`` tokens allocated from a
shared pool with a free list, and each sequence owns a *block table*
(ordered block ids + token count) instead of a contiguous buffer — so a
finishing sequence returns its blocks immediately and a new admission
reuses them, with zero compaction (the paged-attention allocation model).

At-rest quantization reuses the PR-8 EQuARX blockwise codecs verbatim:
one fp32 abs-max scale per ``quant_block`` elements, encoded/decoded
through ``grad_comm._block_kernel_ops()`` — the same seam the collectives
ride, so the pallas codec kernels (ops/pallas/codec.py) apply under
``FLAGS_kernel_autotune`` on TPU targets and the pure-jnp pair stays the
reference everywhere else. Each appended token is quantized exactly once
(scales aligned to token boundaries: ``quant_block`` must divide the
per-token element count), so a token's at-rest bits never change after
the write — which makes an incrementally-maintained dequantized working
copy bit-identical to a fresh :meth:`KVBlockPool.gather` (the engine
relies on this; ``tests/test_serving.py`` pins it).

``append`` returns the *dequantized read-back* of what was stored, never
the input: attention must see exactly the at-rest bits, or the quantized
cache's accuracy story would be fiction.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

__all__ = ["KVBlockPool", "BlockTable", "KVCacheOOM", "KV_CODECS"]

KV_CODECS = ("fp32", "int8_block", "fp8_block")


class KVCacheOOM(RuntimeError):
    """The pool has no free block for a requested allocation."""


@dataclass
class BlockTable:
    """Per-sequence view into the pool: ordered block ids + token count."""

    block_ids: List[int] = field(default_factory=list)
    n_tokens: int = 0

    def capacity(self, block_tokens: int) -> int:
        return len(self.block_ids) * block_tokens


class KVBlockPool:
    """Fixed-size KV block pool with a free list and blockwise codecs.

    One pool per serving replica. ``elems_per_token`` is the flattened
    per-token KV payload (layers x {k,v} x heads x head_dim); callers
    append/gather ``[tokens, elems_per_token]`` fp32 matrices and the
    pool handles block placement and the at-rest codec.
    """

    def __init__(self, n_blocks: int, block_tokens: int,
                 elems_per_token: int, codec: str = "fp32",
                 quant_block: Optional[int] = None):
        from ..distributed import grad_comm

        if codec not in KV_CODECS:
            raise ValueError(f"codec must be one of {KV_CODECS}, got {codec!r}")
        if codec == "fp8_block" and grad_comm._FP8_WIRE is None:
            raise ValueError("fp8_block needs jax float8_e4m3fn support")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.elems_per_token = int(elems_per_token)
        self.codec = codec
        if codec != "fp32":
            qb = int(quant_block or min(self.elems_per_token, 1024))
            if self.elems_per_token % qb:
                raise ValueError(
                    f"quant_block ({qb}) must divide elems_per_token "
                    f"({self.elems_per_token}) so every append stays "
                    f"scale-aligned (tokens quantize exactly once)")
            self.quant_block = qb
            self._scales_per_token = self.elems_per_token // qb
        else:
            self.quant_block = 0
            self._scales_per_token = 0
        shape = (self.n_blocks, self.block_tokens, self.elems_per_token)
        if codec == "fp32":
            self._payload = np.zeros(shape, np.float32)
            self._scales = None
        else:
            wire = np.int8 if codec == "int8_block" else grad_comm._FP8_WIRE
            self._payload = np.zeros(shape, wire)
            self._scales = np.zeros(
                (self.n_blocks,
                 self.block_tokens * self._scales_per_token), np.float32)
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._lock = threading.Lock()

    # ------------------------------------------------------------ allocator
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.n_blocks - len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_tokens)

    def alloc_table(self, n_tokens: int) -> BlockTable:
        """Allocate blocks covering ``n_tokens`` tokens up front (the
        engine reserves a sequence's full context budget at admission so
        decode can never OOM mid-flight)."""
        need = self.blocks_needed(n_tokens)
        with self._lock:
            if need > len(self._free):
                raise KVCacheOOM(
                    f"need {need} blocks, {len(self._free)} free "
                    f"(pool of {self.n_blocks} x {self.block_tokens} tokens)")
            ids = [self._free.pop() for _ in range(need)]
        return BlockTable(block_ids=ids)

    def free_table(self, table: BlockTable):
        with self._lock:
            self._free.extend(table.block_ids)
        table.block_ids = []
        table.n_tokens = 0

    # ---------------------------------------------------------------- codec
    def _encode_chunk(self, chunk: np.ndarray):
        """fp32 [t, ept] -> (payload [t, ept] wire-dtype, scales or None,
        dequantized read-back [t, ept] fp32)."""
        from ..distributed import grad_comm

        if self.codec == "fp32":
            stored = np.ascontiguousarray(chunk, np.float32)
            return stored, None, stored
        flat = chunk.reshape(-1)
        qb = self.quant_block
        absmax = grad_comm.block_absmax(flat, qb)
        scales = grad_comm.block_scales(absmax, self.codec)
        enc, dec = grad_comm._block_kernel_ops()
        q = enc(flat, scales, qb, self.codec)
        deq = np.asarray(dec(q, scales, 1, np.float32, flat.size),
                         np.float32).reshape(chunk.shape)
        wire = self._payload.dtype
        payload = np.asarray(q, dtype=wire).reshape(chunk.shape)
        return payload, np.asarray(scales, np.float32), deq

    def _decode_rows(self, payload: np.ndarray, scales) -> np.ndarray:
        """wire [t, ept] (+scales) -> fp32 [t, ept]."""
        from ..distributed import grad_comm

        if self.codec == "fp32":
            return np.array(payload, np.float32)
        qb = self.quant_block
        carrier = (payload.astype(np.int32) if self.codec == "int8_block"
                   else payload.astype(np.float32))
        _enc, dec = grad_comm._block_kernel_ops()
        numel = payload.size
        out = dec(carrier.reshape(-1, qb), np.asarray(scales, np.float32),
                  1, np.float32, numel)
        return np.asarray(out, np.float32).reshape(payload.shape)

    # ------------------------------------------------------------------- io
    def append(self, table: BlockTable, kv: np.ndarray) -> np.ndarray:
        """Append ``kv`` [t, elems_per_token] fp32 rows to the sequence.
        Returns the dequantized at-rest read-back of the same rows (what
        attention must consume). The table must already hold enough
        blocks (``alloc_table`` reserved them)."""
        kv = np.asarray(kv, np.float32)
        if kv.ndim != 2 or kv.shape[1] != self.elems_per_token:
            raise ValueError(
                f"append wants [t, {self.elems_per_token}], got {kv.shape}")
        t = kv.shape[0]
        if table.n_tokens + t > table.capacity(self.block_tokens):
            raise KVCacheOOM(
                f"table holds {table.capacity(self.block_tokens)} tokens, "
                f"append to {table.n_tokens + t} exceeds the reservation")
        out = np.empty_like(kv)
        done = 0
        with self._lock:
            while done < t:
                pos = table.n_tokens + done
                bi = table.block_ids[pos // self.block_tokens]
                off = pos % self.block_tokens
                take = min(t - done, self.block_tokens - off)
                chunk = kv[done:done + take]
                payload, scales, deq = self._encode_chunk(chunk)
                self._payload[bi, off:off + take] = payload
                if scales is not None:
                    spt = self._scales_per_token
                    self._scales[bi, off * spt:(off + take) * spt] = scales
                out[done:done + take] = deq
                done += take
            table.n_tokens += t
        return out

    def gather(self, table: BlockTable) -> np.ndarray:
        """Dequantize the sequence's full KV prefix -> fp32
        [n_tokens, elems_per_token]."""
        out = np.empty((table.n_tokens, self.elems_per_token), np.float32)
        with self._lock:
            done = 0
            for bi in table.block_ids:
                if done >= table.n_tokens:
                    break
                take = min(self.block_tokens, table.n_tokens - done)
                scales = (None if self._scales is None else
                          self._scales[bi, :take * self._scales_per_token])
                out[done:done + take] = self._decode_rows(
                    self._payload[bi, :take], scales)
                done += take
        return out

    # ----------------------------------------------------------- accounting
    def block_bytes(self) -> int:
        """At-rest bytes of ONE block: payload + its scale slice."""
        b = self.block_tokens * self.elems_per_token * \
            self._payload.dtype.itemsize
        if self._scales is not None:
            b += self.block_tokens * self._scales_per_token * 4
        return b

    def bytes_in_use(self) -> int:
        """At-rest bytes of every allocated block (allocation granularity —
        what the pool actually holds, reservation included)."""
        return self.blocks_in_use * self.block_bytes()

    def fp32_equiv_bytes(self) -> int:
        """What the same allocation would hold un-quantized."""
        return (self.blocks_in_use * self.block_tokens *
                self.elems_per_token * 4)

    def stats(self) -> dict:
        return {
            "codec": self.codec,
            "n_blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "blocks_in_use": self.blocks_in_use,
            "free_blocks": self.free_blocks,
            "bytes_in_use": self.bytes_in_use(),
            "fp32_equiv_bytes": self.fp32_equiv_bytes(),
        }

"""Decode-model adapter: GPTForCausalLM -> jitted prefill/decode steps.

The training model computes full-sequence logits with no KV reuse; serving
needs the split the continuous-batching scheduler works in:

  prefill(ids, lengths)            one pass over the whole prompt ->
                                   logits at the last prompt position +
                                   the per-token KV payload to cache
  decode(ids, pos, past, past_len) one token per sequence against the
                                   cached KV -> next-token logits + the
                                   new token's KV row

Both are pure-jnp jitted functions over a parameter pytree extracted once
from the live model — replicas share the SAME arrays zero-copy (the
``Predictor.clone()`` contract: weights held once, per-replica state is
only the KV pool + scheduler). The block math mirrors ``models.gpt``'s
``_block_apply`` exactly (fp32 layernorm, approximate gelu, einsum
attention) so incremental decode is numerically the training forward;
``tests/test_serving.py`` pins teacher-forced logits parity.

Shapes are static per (batch, context) bucket: callers round batch up to
a power of two and past-context to a power-of-two bucket, so the jit
cache holds a handful of entries instead of one per sequence length.
"""
from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GPTDecodeModel", "bucket_pow2"]

_BLOCK_PARAMS = ("ln1_w", "ln1_b", "qkv_w", "qkv_b", "out_w", "out_b",
                 "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b")


def bucket_pow2(n: int, minimum: int = 1, maximum: int = 0) -> int:
    """Round ``n`` up to a power of two (>= minimum, capped at maximum
    when given) — the jit-cache shape bucket."""
    b = max(int(minimum), 1)
    while b < n:
        b *= 2
    if maximum:
        b = min(b, int(maximum))
    return b


def _ln(v, w, b, eps):
    mu = jnp.mean(v.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(v.astype(jnp.float32), axis=-1, keepdims=True)
    out = (v.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
    return (out * w + b).astype(v.dtype)


class GPTDecodeModel:
    """Serving adapter over a loop- or scan-mode GPTForCausalLM."""

    def __init__(self, model):
        cfg = model.config
        self.config = cfg
        self.n_layers = cfg.num_layers
        self.n_heads = cfg.num_heads
        self.head_dim = cfg.head_dim
        self.hidden = cfg.hidden_size
        self.vocab_size = cfg.vocab_size
        self.max_context = cfg.max_position_embeddings
        # per-token KV payload: layers x {k, v} x heads x head_dim
        self.elems_per_token = self.n_layers * 2 * self.hidden
        self._eps = cfg.layer_norm_epsilon
        self.params = self._extract(model)
        self._jit_steps()

    def _jit_steps(self):
        self._prefill_fn = jax.jit(self._make_prefill())
        self._decode_fn = jax.jit(self._make_decode())
        self._extend_fn = jax.jit(self._make_extend())

    def truncated(self, n_layers: int) -> "GPTDecodeModel":
        """A draft model from this model's own weights: the first
        ``n_layers`` decoder blocks under the same embeddings and final
        norm (zero new parameters — the serving analog of early-exit
        self-drafting). Its KV payload is proportionally smaller
        (``elems_per_token = n_layers * 2 * hidden``); it is NOT paged —
        the engine keeps a small dense draft cache per sequence."""
        if not (0 < int(n_layers) <= self.n_layers):
            raise ValueError(
                f"truncated wants 1..{self.n_layers} layers, got {n_layers}")
        new = object.__new__(GPTDecodeModel)
        new.config = self.config
        new.n_layers = int(n_layers)
        new.n_heads = self.n_heads
        new.head_dim = self.head_dim
        new.hidden = self.hidden
        new.vocab_size = self.vocab_size
        new.max_context = self.max_context
        new.elems_per_token = new.n_layers * 2 * new.hidden
        new._eps = self._eps
        new.params = dict(self.params)
        for name in _BLOCK_PARAMS:
            new.params[name] = self.params[name][:new.n_layers]
        new._jit_steps()
        return new

    # ------------------------------------------------------------ params
    def _extract(self, model) -> dict:
        emb = model.gpt.embeddings
        p = {
            "word": emb.word_embeddings._value,
            "pos": emb.position_embeddings._value,
            "final_w": model.gpt.final_norm.weight._value,
            "final_b": model.gpt.final_norm.bias._value,
        }
        dec = model.gpt.decoder
        if hasattr(dec, "cfg"):  # scan mode: already layer-stacked
            for name in _BLOCK_PARAMS:
                p[name] = getattr(dec, name)._value
        else:  # loop mode: LayerList of GPTDecoderLayer
            for name in _BLOCK_PARAMS:
                p[name] = jnp.stack(
                    [getattr(layer, name)._value for layer in dec])
        return p

    def param_list(self) -> list:
        """Flat deterministic parameter list (ReplicaGuard digests)."""
        return [self.params[k] for k in sorted(self.params)]

    # ------------------------------------------------------- traced steps
    def _make_prefill(self):
        L, n, d = self.n_layers, self.n_heads, self.head_dim
        eps, scale = self._eps, 1.0 / math.sqrt(self.head_dim)

        def fn(params, ids, lengths):
            b, s = ids.shape
            x = jnp.take(params["word"], ids, axis=0) + params["pos"][:s]

            def body(carry, pl):
                x = carry
                hn = _ln(x, pl["ln1_w"], pl["ln1_b"], eps)
                qkv = jnp.einsum("bsh,hcj->bscj", hn, pl["qkv_w"]) \
                    + pl["qkv_b"]
                qkv = qkv.reshape(b, s, 3, n, d)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
                causal = jnp.tril(jnp.ones((s, s), dtype=bool))
                logits = jnp.where(causal, logits,
                                   jnp.finfo(logits.dtype).min)
                probs = jax.nn.softmax(logits.astype(jnp.float32),
                                       axis=-1).astype(v.dtype)
                attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
                y = attn.reshape(b, s, n * d) @ pl["out_w"] + pl["out_b"]
                x = x + y
                hn = _ln(x, pl["ln2_w"], pl["ln2_b"], eps)
                z = hn @ pl["fc1_w"] + pl["fc1_b"]
                z = jax.nn.gelu(z, approximate=True)
                z = z @ pl["fc2_w"] + pl["fc2_b"]
                return x + z, (k, v)

            stacked = {name: params[name] for name in _BLOCK_PARAMS}
            x, (ks, vs) = jax.lax.scan(body, x, stacked)
            x = _ln(x, params["final_w"], params["final_b"], eps)
            logits = x @ params["word"].T                      # [b, s, V]
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            # [L,b,s,n,d] x2 -> [L,b,2,s,n,d] -> [b,s,L,2,n,d] -> [b,s,ept]
            kv = jnp.stack([ks, vs], axis=2)
            kv = kv.transpose(1, 3, 0, 2, 4, 5).reshape(
                b, s, self.elems_per_token)
            return last, kv, logits

        return fn

    def _make_decode(self):
        L, n, d = self.n_layers, self.n_heads, self.head_dim
        eps, scale = self._eps, 1.0 / math.sqrt(self.head_dim)

        def fn(params, ids, pos, past, past_len):
            b = ids.shape[0]
            S = past.shape[1]
            x = jnp.take(params["word"], ids, axis=0) \
                + jnp.take(params["pos"], pos, axis=0)         # [b, h]
            past_r = past.reshape(b, S, L, 2, n, d)
            pk = past_r[:, :, :, 0].transpose(2, 0, 1, 3, 4)   # [L,b,S,n,d]
            pv = past_r[:, :, :, 1].transpose(2, 0, 1, 3, 4)
            valid = jnp.arange(S)[None, :] < past_len[:, None]  # [b, S]
            mask = jnp.concatenate(
                [valid, jnp.ones((b, 1), bool)], axis=1)[:, None, :]

            def body(carry, inp):
                x = carry
                pl, k_past, v_past = inp
                hn = _ln(x, pl["ln1_w"], pl["ln1_b"], eps)
                qkv = jnp.einsum("bh,hcj->bcj", hn, pl["qkv_w"]) \
                    + pl["qkv_b"]
                qkv = qkv.reshape(b, 3, n, d)
                q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
                lp = jnp.einsum("bnd,bsnd->bns", q, k_past) * scale
                ls = jnp.sum(q * k, axis=-1, keepdims=True) * scale
                al = jnp.concatenate([lp, ls], axis=-1)        # [b,n,S+1]
                al = jnp.where(mask, al, jnp.finfo(al.dtype).min)
                probs = jax.nn.softmax(al.astype(jnp.float32),
                                       axis=-1).astype(v.dtype)
                attn = jnp.einsum("bns,bsnd->bnd", probs[:, :, :S], v_past) \
                    + probs[:, :, S:] * v
                y = attn.reshape(b, n * d) @ pl["out_w"] + pl["out_b"]
                x = x + y
                hn = _ln(x, pl["ln2_w"], pl["ln2_b"], eps)
                z = hn @ pl["fc1_w"] + pl["fc1_b"]
                z = jax.nn.gelu(z, approximate=True)
                z = z @ pl["fc2_w"] + pl["fc2_b"]
                return x + z, (k, v)

            stacked = {name: params[name] for name in _BLOCK_PARAMS}
            x, (ks, vs) = jax.lax.scan(body, x, (stacked, pk, pv))
            x = _ln(x, params["final_w"], params["final_b"], eps)
            logits = x @ params["word"].T                      # [b, V]
            # [L,b,n,d] x2 -> [L,b,2,n,d] -> [b,L,2,n,d] -> [b,ept]
            kv = jnp.stack([ks, vs], axis=2)
            kv = kv.transpose(1, 0, 2, 3, 4).reshape(
                b, self.elems_per_token)
            return logits, kv

        return fn

    def _make_extend(self):
        """Multi-token incremental step: ``s`` new tokens per row attend
        to the cached past AND causally within the tail — ``decode``
        generalized from one token to a ragged tail. One program serves
        both prefix-cache tail prefill (prompt minus the cached prefix)
        and speculative verification (target scores k+1 draft positions
        in one bucketed forward)."""
        L, n, d = self.n_layers, self.n_heads, self.head_dim
        eps, scale = self._eps, 1.0 / math.sqrt(self.head_dim)

        def fn(params, ids, pos, past, past_len, tail_len):
            b, s = ids.shape
            S = past.shape[1]
            x = jnp.take(params["word"], ids, axis=0) \
                + jnp.take(params["pos"], pos, axis=0)       # [b, s, h]
            past_r = past.reshape(b, S, L, 2, n, d)
            pk = past_r[:, :, :, 0].transpose(2, 0, 1, 3, 4)  # [L,b,S,n,d]
            pv = past_r[:, :, :, 1].transpose(2, 0, 1, 3, 4)
            valid_past = (jnp.arange(S)[None, :]
                          < past_len[:, None])[:, None, None, :]  # [b,1,1,S]
            causal = jnp.tril(jnp.ones((s, s), dtype=bool))
            tail_ok = jnp.arange(s)[None, :] < tail_len[:, None]  # [b, s]
            mask_tail = causal[None, None, :, :] \
                & tail_ok[:, None, None, :]                  # [b,1,s,s]

            def body(carry, inp):
                x = carry
                pl, k_past, v_past = inp
                hn = _ln(x, pl["ln1_w"], pl["ln1_b"], eps)
                qkv = jnp.einsum("bsh,hcj->bscj", hn, pl["qkv_w"]) \
                    + pl["qkv_b"]
                qkv = qkv.reshape(b, s, 3, n, d)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                lp = jnp.einsum("bqnd,bknd->bnqk", q, k_past) * scale
                lt = jnp.einsum("bqnd,bknd->bnqk", q, k) * scale
                neg = jnp.finfo(lp.dtype).min
                al = jnp.concatenate(
                    [jnp.where(valid_past, lp, neg),
                     jnp.where(mask_tail, lt, neg)], axis=-1)  # [b,n,s,S+s]
                probs = jax.nn.softmax(al.astype(jnp.float32),
                                       axis=-1).astype(v.dtype)
                attn = jnp.einsum("bnqk,bknd->bqnd", probs[..., :S], v_past) \
                    + jnp.einsum("bnqk,bknd->bqnd", probs[..., S:], v)
                y = attn.reshape(b, s, n * d) @ pl["out_w"] + pl["out_b"]
                x = x + y
                hn = _ln(x, pl["ln2_w"], pl["ln2_b"], eps)
                z = hn @ pl["fc1_w"] + pl["fc1_b"]
                z = jax.nn.gelu(z, approximate=True)
                z = z @ pl["fc2_w"] + pl["fc2_b"]
                return x + z, (k, v)

            stacked = {name: params[name] for name in _BLOCK_PARAMS}
            x, (ks, vs) = jax.lax.scan(body, x, (stacked, pk, pv))
            x = _ln(x, params["final_w"], params["final_b"], eps)
            logits = x @ params["word"].T                    # [b, s, V]
            # [L,b,s,n,d] x2 -> [b,s,L,2,n,d] -> [b,s,ept]
            kv = jnp.stack([ks, vs], axis=2)
            kv = kv.transpose(1, 3, 0, 2, 4, 5).reshape(
                b, s, self.elems_per_token)
            return logits, kv

        return fn

    # ------------------------------------------------------- host surface
    def prefill(self, prompts: Sequence[np.ndarray]
                ) -> Tuple[np.ndarray, List[np.ndarray]]:
        """Batch-prefill prompts (host pads to shape buckets). Returns
        (last-position logits [n, V], per-sequence KV [s_i, ept])."""
        n_seq = len(prompts)
        lengths = np.array([len(p) for p in prompts], np.int32)
        if lengths.min() < 1:
            raise ValueError("empty prompt")
        if lengths.max() > self.max_context:
            raise ValueError(
                f"prompt of {lengths.max()} tokens exceeds max_context "
                f"{self.max_context}")
        b = bucket_pow2(n_seq)
        s = bucket_pow2(int(lengths.max()), minimum=8,
                        maximum=self.max_context)
        ids = np.zeros((b, s), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = np.asarray(p, np.int32)
        lens = np.ones((b,), np.int32)
        lens[:n_seq] = lengths
        last, kv, _ = self._prefill_fn(self.params, jnp.asarray(ids),
                                       jnp.asarray(lens))
        last = np.asarray(last)
        kv = np.asarray(kv)
        return last[:n_seq], [kv[i, :lengths[i]] for i in range(n_seq)]

    def forced_logits(self, ids: np.ndarray) -> np.ndarray:
        """Full-sequence logits [b, s, V] (parity tests / scoring)."""
        ids = np.asarray(ids, np.int32)
        lens = np.full((ids.shape[0],), ids.shape[1], np.int32)
        _, _, logits = self._prefill_fn(self.params, jnp.asarray(ids),
                                        jnp.asarray(lens))
        return np.asarray(logits)

    def decode(self, ids: np.ndarray, pos: np.ndarray, past: np.ndarray,
               past_len: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One decode step for a (bucketed) batch. ``past`` is
        [b, S, ept] fp32 (dequantized working copy), ``past_len`` the
        per-row valid prefix. Returns (logits [b, V], new KV [b, ept])."""
        logits, kv = self._decode_fn(
            self.params, jnp.asarray(ids, np.int32),
            jnp.asarray(pos, np.int32), jnp.asarray(past, np.float32),
            jnp.asarray(past_len, np.int32))
        return np.asarray(logits), np.asarray(kv)

    def extend(self, ids: np.ndarray, pos: np.ndarray, past: np.ndarray,
               past_len: np.ndarray, tail_len: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Multi-token step for a (bucketed) batch: ``ids``/``pos`` are
        [b, s] tails, ``past`` [b, S, ept] fp32 with ``past_len`` valid
        rows, ``tail_len`` the per-row valid tail. Returns
        (logits [b, s, V], new KV [b, s, ept]); rows past ``tail_len``
        are padding garbage the caller must ignore."""
        logits, kv = self._extend_fn(
            self.params, jnp.asarray(ids, np.int32),
            jnp.asarray(pos, np.int32), jnp.asarray(past, np.float32),
            jnp.asarray(past_len, np.int32),
            jnp.asarray(tail_len, np.int32))
        return np.asarray(logits), np.asarray(kv)

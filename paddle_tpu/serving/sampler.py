"""Batched top-k/top-p/temperature sampling with per-request RNG streams.

The ONE token-selection entry point for the serving engine: prefill
first-tokens, decode steps, and speculative verification all route
through :meth:`BatchSampler.sample`, so EOS/max_new/token-retirement
policy lives in exactly one place (the engine's commit path) and the
greedy/stochastic split cannot drift between call sites.

Randomness contract (the serving fork of the PR-4 ``framework/random``
stream machinery): every sampled token draws its PRNG key as a pure
function of (sampler seed, request identity, token position) via
``framework.random.CounterKeyStream`` semantics — double ``fold_in`` on
a base key. No mutable stream state exists, so a request's token
sequence is deterministic regardless of which decode batch it lands in,
which replica runs it, or how often it is evicted and replayed
(``reincarnate()`` keeps the request id, and the id IS the stream).

Greedy is the temperature<=0 fast path: an all-greedy batch never
touches the jitted sampler and reproduces the historical
``np.argmax(logits)`` behavior bit-for-bit; mixed batches route greedy
rows through ``jnp.argmax`` inside the same compiled program.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .model import bucket_pow2

__all__ = ["SamplingParams", "BatchSampler", "GREEDY"]


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy. Defaults are exact greedy."""

    temperature: float = 0.0   # <= 0 -> argmax (deterministic fast path)
    top_k: int = 0             # 0 -> disabled (full vocabulary)
    top_p: float = 1.0         # 1.0 -> disabled (no nucleus cut)
    seed: Optional[int] = None  # None -> derived from the request id

    def __post_init__(self):
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")


GREEDY = SamplingParams()


def _ident(identity) -> int:
    """Request identity -> 32-bit stream id (CounterKeyStream._ident)."""
    if isinstance(identity, str):
        return zlib.crc32(identity.encode("utf-8"))
    return int(identity) & 0xFFFFFFFF


def _make_sample_fn(seed: int):
    """jitted [B, V] batch sampler; per-row keys derived in-program."""

    def fn(logits, temps, top_ks, top_ps, idents, counters):
        V = logits.shape[-1]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        base = jax.random.key(seed)
        keys = jax.vmap(
            lambda i, c: jax.random.fold_in(jax.random.fold_in(base, i), c)
        )(idents, counters)
        t = jnp.maximum(temps, 1e-6)[:, None]
        scaled = logits.astype(jnp.float32) / t
        # top-k: drop everything below the kth-largest logit (0 = off)
        by_rank = -jnp.sort(-scaled, axis=-1)
        k = jnp.where(top_ks > 0, top_ks, V)
        kth = jnp.take_along_axis(
            by_rank, jnp.clip(k - 1, 0, V - 1)[:, None], axis=-1)
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        # top-p nucleus in sorted space: keep tokens whose cumulative
        # probability BEFORE them is < top_p (the head token always stays)
        order = jnp.argsort(-scaled, axis=-1)
        slg = jnp.take_along_axis(scaled, order, axis=-1)
        probs = jax.nn.softmax(slg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        slg = jnp.where((cum - probs) < top_ps[:, None], slg, -jnp.inf)
        idx = jax.vmap(jax.random.categorical)(keys, slg)
        tok = jnp.take_along_axis(
            order, idx[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, tok)

    return fn


class BatchSampler:
    """Batched sampler over one deterministic key space.

    One instance per serving process is enough (it is stateless beyond
    the jit cache); engines share the default instance unless a test
    pins its own seed.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._fn = jax.jit(_make_sample_fn(self.seed))

    def key_for(self, params: SamplingParams, identity, position: int):
        """The exact PRNG key row ``sample`` uses — exposed so tests can
        reproduce a single draw out-of-band."""
        ident = _ident(params.seed if params.seed is not None else identity)
        base = jax.random.key(self.seed)
        return jax.random.fold_in(jax.random.fold_in(base, ident),
                                  int(position))

    def sample(self, logits: np.ndarray,
               params: Sequence[SamplingParams],
               identities: Sequence,
               positions: Sequence[int]) -> np.ndarray:
        """Sample one token per row of ``logits`` [n, V].

        ``identities[i]`` names row i's RNG stream (request id unless the
        request pinned an explicit seed); ``positions[i]`` is the index of
        the token being sampled within that request's generation — the
        stream counter. Returns int32 [n].
        """
        n = logits.shape[0]
        if n != len(params) or n != len(identities) or n != len(positions):
            raise ValueError("sample wants one (params, identity, position) "
                             "per logits row")
        temps = np.array([p.temperature for p in params], np.float32)
        if not (temps > 0.0).any():
            # all-greedy fast path: bit-identical to the historical
            # host-side np.argmax, zero device dispatches
            return np.argmax(logits, axis=-1).astype(np.int32)
        B = bucket_pow2(n)
        lg = np.full((B, logits.shape[1]), -1e30, np.float32)
        lg[:n] = logits
        t = np.zeros((B,), np.float32)
        ks = np.zeros((B,), np.int32)
        ps = np.ones((B,), np.float32)
        ids = np.zeros((B,), np.uint32)
        ctr = np.zeros((B,), np.int32)
        t[:n] = temps
        ks[:n] = [p.top_k for p in params]
        ps[:n] = [p.top_p for p in params]
        ids[:n] = [_ident(p.seed if p.seed is not None else ident)
                   for p, ident in zip(params, identities)]
        ctr[:n] = np.asarray(positions, np.int32)
        out = self._fn(jnp.asarray(lg), jnp.asarray(t), jnp.asarray(ks),
                       jnp.asarray(ps), jnp.asarray(ids), jnp.asarray(ctr))
        return np.asarray(out)[:n]


_default: Optional[BatchSampler] = None


def default_sampler() -> BatchSampler:
    """Process-wide sampler (lazy: jit setup must not run at import)."""
    global _default
    if _default is None:
        _default = BatchSampler(seed=0)
    return _default

"""Request queue + admission control for the serving runtime.

One process-wide FIFO feeds every replica. Admission happens at
``submit``: a full queue rejects immediately (open-loop traffic must get
backpressure at the door, not time out after queueing — the classic
admission-control contract), counted as
``serve_requests_total{outcome="rejected"}``. A replica eviction puts the
drained in-flight requests back at the FRONT of the queue (they were
already admitted; re-admission must not re-run the depth check or they
could be silently dropped — the zero-lost-requests guarantee).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..observability.metrics import get_registry as _get_registry
from ..observability.tracing import get_tracer as _get_tracer
from .sampler import GREEDY, SamplingParams

__all__ = ["ServeRequest", "RequestQueue", "OUTCOMES"]

OUTCOMES = ("completed", "rejected", "requeued", "failed")

_req_counter = itertools.count()

_m_requests = _get_registry().counter(
    "serve_requests_total",
    "serving requests by terminal/requeue outcome", labels=("outcome",))
_m_queue_depth = _get_registry().gauge(
    "serve_queue_depth", "requests waiting for admission to a decode batch")


def count_outcome(outcome: str, n: int = 1):
    if outcome not in OUTCOMES:
        raise ValueError(f"outcome must be one of {OUTCOMES}, got {outcome!r}")
    _m_requests.labels(outcome=outcome).inc(n)


@dataclass
class ServeRequest:
    """One generation request plus its serving bookkeeping."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    request_id: str = field(
        default_factory=lambda: f"req-{next(_req_counter)}")
    # sampling policy; the request_id names the RNG stream unless
    # ``sampling.seed`` pins one, so tokens are deterministic across
    # batch placement, replicas, and eviction/replay
    sampling: SamplingParams = GREEDY
    # -- bookkeeping (owned by the runtime) --
    t_submit: float = 0.0
    t_enqueue: float = 0.0  # last time this attempt entered the queue
    t_first_token: float = 0.0
    t_done: float = 0.0
    generated: List[int] = field(default_factory=list)
    outcome: str = ""
    attempts: int = 0
    error: str = ""
    # request-scoped trace (observability/tracing.py): minted at submit,
    # carried across eviction/reincarnation so one request = one timeline
    trace: Optional[object] = None

    @property
    def n_prompt(self) -> int:
        return len(self.prompt_ids)

    @property
    def context_budget(self) -> int:
        """Max tokens this request can ever hold in the KV cache: the
        prompt plus every token it may generate except the last (whose KV
        is never appended — the sequence ends at its logits)."""
        return self.n_prompt + max(0, self.max_new_tokens - 1)

    @property
    def latency_ms(self) -> float:
        return (self.t_done - self.t_submit) * 1e3 if self.t_done else 0.0

    def reincarnate(self) -> "ServeRequest":
        """Fresh attempt of a drained request (replica eviction): same
        identity and submit time — latency is measured from the ORIGINAL
        arrival, retries are not free — but clean generation state. A new
        object so the evicted replica's zombie thread, which may still
        hold the old one inside a hung step, cannot race the re-run."""
        return ServeRequest(
            prompt_ids=self.prompt_ids, max_new_tokens=self.max_new_tokens,
            eos_id=self.eos_id, request_id=self.request_id,
            sampling=self.sampling,
            t_submit=self.t_submit, attempts=self.attempts + 1,
            trace=self.trace)


class RequestQueue:
    """Bounded thread-safe FIFO with front re-admission."""

    def __init__(self, max_depth: int = 256):
        self.max_depth = int(max_depth)
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def __len__(self):
        return len(self._q)

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, req: ServeRequest) -> bool:
        """Admission control: False (and a ``rejected`` count) when the
        queue is at depth; True once the request is accepted."""
        with self._cond:
            if self._closed or len(self._q) >= self.max_depth:
                count_outcome("rejected")
                return False
            if not req.t_submit:
                req.t_submit = time.monotonic()
            req.t_enqueue = time.monotonic()
            if req.trace is None:
                req.trace = _get_tracer().start_trace(
                    "serve_request", request_id=req.request_id,
                    n_prompt=req.n_prompt,
                    max_new_tokens=req.max_new_tokens)
            self._q.append(req)
            _m_queue_depth.set(len(self._q))
            self._cond.notify()
        return True

    def requeue_front(self, reqs: List[ServeRequest], count: bool = True):
        """Re-admit requests at the head (no depth check — they were
        already accepted; eviction must not lose them). ``count=False``
        for a scheduler put-back (no KV room this tick), which is flow
        control, not a drain."""
        with self._cond:
            now = time.monotonic()
            for r in reversed(reqs):
                r.t_enqueue = now
                self._q.appendleft(r)
            _m_queue_depth.set(len(self._q))
            if reqs:
                if count:
                    count_outcome("requeued", len(reqs))
                    tracer = _get_tracer()
                    for r in reqs:
                        tracer.record_span(r.trace, "requeue_front",
                                           attempt=r.attempts)
                self._cond.notify_all()

    def pop_nowait(self) -> Optional[ServeRequest]:
        with self._cond:
            if not self._q:
                return None
            r = self._q.popleft()
            _m_queue_depth.set(len(self._q))
            return r

    def wait_nonempty(self, timeout: float) -> bool:
        """Block until the queue has work (or timeout/close); the popper
        still races other replicas via ``pop_nowait``."""
        with self._cond:
            if self._q or self._closed:
                return bool(self._q)
            self._cond.wait(timeout)
            return bool(self._q)

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

"""Multi-replica dispatch: N engines behind one queue, with eviction.

The serving analog of PR-4's training fault model. Each replica is a
``ServingEngine`` driven by its own daemon worker thread; all replicas
share the decode model's parameter arrays zero-copy (``Predictor.clone``
semantics — per-replica state is only the KV pool + batch) and race for
work on one admission-controlled ``RequestQueue``.

Failure handling — a replica leaves the set, its work does not:

  hang     a per-replica ``robustness.watchdog.HangDetector`` beats once
           per scheduler tick; a step stuck past the timeout evicts the
           replica from the detector's poll thread.
  corrupt  a ``robustness.distributed_ft.ReplicaGuard`` (policy="raise")
           digests the replica's parameters every ``guard_every`` steps
           against the set's boot-time reference digest — the serving
           variant of the SDC check, with the reference playing the role
           of the agreeing peer.
  error    any exception escaping ``engine.step()``.

Eviction = ``engine.drain()`` (fences the zombie thread via the engine's
``alive`` flag — a stuck step that wakes later cannot commit results) +
fresh copies of every in-flight request re-admitted at the queue head
for the surviving replicas. An accepted request is therefore never lost
(``tests/test_serving.py`` chaos cases pin zero-lost under hang, crash,
and corruption).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..observability.events import get_event_log
from ..observability.metrics import get_registry as _get_registry
from ..observability.tracing import get_tracer as _get_tracer
from .engine import ServingEngine
from .kv_cache import KVBlockPool
from .model import GPTDecodeModel
from .scheduler import RequestQueue, ServeRequest

__all__ = ["ReplicaSet"]

_m_evictions = _get_registry().counter(
    "serve_replica_evictions_total", "replicas evicted from the set",
    labels=("reason",))
_m_scale_events = _get_registry().counter(
    "serve_scale_events_total",
    "policy-driven replica scale events (fleet controller)",
    labels=("direction",))


class ReplicaSet:
    """N serving replicas behind one request queue."""

    def __init__(self, model: GPTDecodeModel, n_replicas: int = 2,
                 queue: Optional[RequestQueue] = None,
                 n_blocks: int = 64, block_tokens: Optional[int] = None,
                 codec: Optional[str] = None,
                 max_batch: Optional[int] = None,
                 watchdog_timeout: Optional[float] = None,
                 guard_every: int = 0,
                 models: Optional[List[GPTDecodeModel]] = None,
                 pre_step_hooks: Optional[Dict[int, Callable]] = None,
                 prefix_cache: Optional[bool] = None,
                 draft_model: Optional[GPTDecodeModel] = None,
                 spec_k: Optional[int] = None,
                 sampler=None,
                 compile_grace: Optional[float] = None):
        from ..framework.flags import flag

        self.model = model
        # `is not None`, NOT truthiness: an EMPTY RequestQueue is falsy
        # (__len__ == 0), and `queue or ...` would silently replace the
        # caller's queue with a private one
        self.queue = queue if queue is not None else RequestQueue(
            max_depth=int(flag("FLAGS_serving_queue_depth", 256)))
        block_tokens = int(block_tokens
                           or flag("FLAGS_serving_block_tokens", 16))
        self.codec = codec or str(flag("FLAGS_serving_kv_codec", "fp32"))
        self.watchdog_timeout = float(
            watchdog_timeout or flag("FLAGS_serving_watchdog_s", 30.0))
        self.compile_grace = float(
            compile_grace if compile_grace is not None
            else flag("FLAGS_serving_compile_grace_s", 120.0))
        self.guard_every = int(guard_every)
        # kept for scale_up: a policy-grown replica gets the same pool
        # and batch geometry as the boot-time ones
        self._n_blocks = int(n_blocks)
        self._block_tokens = block_tokens
        self._max_batch = max_batch
        self._sampler = sampler
        self._prefix_cache = prefix_cache
        self._draft = draft_model
        self._spec_k = spec_k
        self._models = list(models) if models else [model] * n_replicas
        if len(self._models) != n_replicas:
            raise ValueError("models override must have one entry per "
                             "replica")
        self._hooks = dict(pre_step_hooks or {})
        self.engines: List[ServingEngine] = []
        for i in range(n_replicas):
            self.engines.append(self._new_engine(i, self._models[i]))
        self.results: Dict[str, ServeRequest] = {}
        self.evictions: List[dict] = []
        self.scale_events: List[dict] = []
        self._results_cond = threading.Condition()
        self._evict_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._hds: list = []
        self._ref_digest = None

    def _new_engine(self, idx: int, model: GPTDecodeModel) -> ServingEngine:
        pool = KVBlockPool(n_blocks=self._n_blocks,
                           block_tokens=self._block_tokens,
                           elems_per_token=model.elems_per_token,
                           codec=self.codec)
        # the draft model (like the target) is stateless jitted
        # params — shared zero-copy; per-replica draft state is only
        # the per-sequence dense mirrors inside the engine
        return ServingEngine(
            model, pool, self.queue, max_batch=self._max_batch,
            name=f"replica-{idx}", pre_step=self._hooks.get(idx),
            on_finish=self._on_finish, sampler=self._sampler,
            prefix_cache=self._prefix_cache, draft_model=self._draft,
            spec_k=self._spec_k)

    # ------------------------------------------------------------ lifecycle
    def _spawn_worker(self, idx: int):
        """Arm a compile-grace-aware watchdog + daemon worker for one
        engine (boot-time and scale_up share this path)."""
        from ..robustness.watchdog import HangDetector

        eng = self.engines[idx]
        hd = HangDetector(
            timeout=self.watchdog_timeout,
            on_hang=lambda age, i=idx: self.evict(i, "hang"),
            state_fn=lambda e=eng: e.state,
            compile_grace=self.compile_grace)
        self._hds.append(hd)
        hd.start()
        t = threading.Thread(target=self._worker, args=(idx,),
                             daemon=True, name=f"serve-{eng.name}")
        self._threads.append(t)
        t.start()

    def start(self) -> "ReplicaSet":
        from ..observability import exposition
        from ..robustness.distributed_ft import params_digest

        if self._threads:
            return self
        if self.guard_every:
            self._ref_digest = params_digest(self.model.param_list())
        for i in range(len(self.engines)):
            self._spawn_worker(i)
        exposition.register_section("serving", self.stats)
        # /traces (index) + /traces/<id> (one request's full span list),
        # read-only over the bounded trace store, mounted for the set's
        # lifetime like /serving
        exposition.register_section(
            "traces", lambda: _get_tracer().store.index(),
            lambda tid: _get_tracer().store.get(tid))
        return self

    def stop(self):
        self._stop.set()
        self.queue.close()
        for hd in self._hds:
            hd.stop()
        for t in self._threads:
            t.join(timeout=5)
        from ..observability import exposition

        exposition.unregister_section("serving")
        exposition.unregister_section("traces")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- workers
    def _worker(self, idx: int):
        from ..robustness.distributed_ft import (
            ReplicaDivergenceError, ReplicaGuard,
        )

        eng = self.engines[idx]
        hd = self._hds[idx]
        guard = None
        if self.guard_every:
            ref = self._ref_digest

            def against_ref(digest):
                import numpy as np

                return (np.minimum(digest, ref), np.maximum(digest, ref))

            guard = ReplicaGuard(policy="raise", every_n=self.guard_every,
                                 reduce_fn=against_ref)
        while not self._stop.is_set() and eng.alive:
            try:
                if guard is not None:
                    guard.maybe_check(self._models[idx].param_list(),
                                      step=eng.steps)
                worked = eng.step()
            except ReplicaDivergenceError:
                self.evict(idx, "corrupt")
                return
            except Exception as e:  # any escaped step error evicts
                get_event_log().error("serving", "replica step failed",
                                      replica=eng.name, error=repr(e))
                self.evict(idx, "error")
                return
            hd.beat()
            if not worked:
                self.queue.wait_nonempty(0.02)

    # ------------------------------------------------------------- eviction
    def evict(self, idx: int, reason: str):
        """Remove a replica: fence it, drain its in-flight requests, and
        re-admit them at the queue head. Idempotent per replica."""
        eng = self.engines[idx]
        with self._evict_lock:
            if not eng.alive:
                return
            drained = eng.drain()
        tracer = _get_tracer()
        for r in drained:
            tracer.record_span(r.trace, "eviction", replica=eng.name,
                               reason=reason, attempt=r.attempts)
        # requeue FIRST — nothing below may stand between a drained
        # request and its re-admission. The detector is disarmed without
        # a join: eviction often runs ON its poll thread (on_hang).
        self.queue.requeue_front(drained)
        if idx < len(self._hds):
            self._hds[idx]._stop.set()
        _m_evictions.labels(reason=reason).inc()
        self.evictions.append({"replica": eng.name, "reason": reason,
                               "drained": len(drained)})
        get_event_log().error(
            "serving", "replica evicted", replica=eng.name, reason=reason,
            drained=len(drained))

    # ------------------------------------------------------------- scaling
    # Policy-driven capacity changes (ISSUE 17 fleet controller). Scale
    # DOWN goes through the exact eviction mechanics — fence + drain +
    # requeue_front — so the zero-lost-requests guarantee is the same
    # machine-checked path (analysis rule F004), just with a "scale"
    # ledger entry instead of a failure reason.
    def scale_down(self, idx: Optional[int] = None,
                   reason: str = "scale_down") -> Optional[dict]:
        """Retire one replica without losing work. Defaults to the
        highest-index alive replica (deterministic for trace replay).
        Returns the scale-event record, or None if nothing was alive."""
        if idx is None:
            alive = [i for i, e in enumerate(self.engines) if e.alive]
            if not alive:
                return None
            idx = alive[-1]
        eng = self.engines[idx]
        with self._evict_lock:
            if not eng.alive:
                return None
            drained = eng.drain()
        tracer = _get_tracer()
        for r in drained:
            tracer.record_span(r.trace, "scale_down", replica=eng.name,
                               reason=reason, attempt=r.attempts)
        self.queue.requeue_front(drained)
        if idx < len(self._hds):
            self._hds[idx]._stop.set()
        _m_scale_events.labels(direction="down").inc()
        ev = {"replica": eng.name, "direction": "down", "reason": reason,
              "drained": len(drained)}
        self.scale_events.append(ev)
        get_event_log().info(
            "serving", "replica scaled down", replica=eng.name,
            reason=reason, drained=len(drained))
        return ev

    def scale_up(self, model: Optional[GPTDecodeModel] = None,
                 reason: str = "scale_up") -> int:
        """Boot one more replica (fresh engine + KV pool; weights shared
        zero-copy). If the set is running, a worker thread and a
        compile-aware watchdog arm immediately — the new replica reports
        ``compiling`` on its first step, so the extended first-poll
        deadline covers its cold compile. Returns the new replica index."""
        model = model if model is not None else self.model
        idx = len(self.engines)
        self.engines.append(self._new_engine(idx, model))
        self._models.append(model)
        if self._threads:  # live set: arm watchdog + worker like start()
            self._spawn_worker(idx)
        _m_scale_events.labels(direction="up").inc()
        ev = {"replica": self.engines[idx].name, "direction": "up",
              "reason": reason, "drained": 0}
        self.scale_events.append(ev)
        get_event_log().info(
            "serving", "replica scaled up", replica=self.engines[idx].name,
            reason=reason, replicas=self.alive_replicas)
        return idx

    def pump(self, ticks: int = 1) -> int:
        """Synchronous driving mode: step every alive engine in index
        order, no worker threads. Deterministic harnesses (the fleet
        chaos phase) drive the set from a trace clock through this
        instead of ``start()``; both modes share admit/decode/drain
        mechanics. Returns how many engine steps did work."""
        worked = 0
        for _ in range(int(ticks)):
            for eng in self.engines:
                if eng.alive and eng.step():
                    worked += 1
        return worked

    @property
    def alive_replicas(self) -> int:
        return sum(1 for e in self.engines if e.alive)

    def heartbeat_ages(self) -> List[float]:
        """Seconds since each armed watchdog last saw its replica beat
        (disarmed/evicted detectors excluded). The fleet SignalsAdapter
        reads the max as an early-warning hang signal — a replica whose
        age approaches the watchdog timeout is about to be evicted."""
        import time

        now = time.monotonic()
        return [now - hd._last for hd in self._hds
                if not hd._stop.is_set()]

    # -------------------------------------------------------------- serving
    def submit(self, req: ServeRequest) -> bool:
        return self.queue.submit(req)

    def _on_finish(self, engine: ServingEngine, req: ServeRequest):
        with self._results_cond:
            self.results[req.request_id] = req
            self._results_cond.notify_all()

    def wait(self, request_ids, timeout: float = 60.0
             ) -> Dict[str, ServeRequest]:
        """Block until every id has a terminal result (or timeout);
        returns the results seen so far either way."""
        import time

        deadline = time.monotonic() + timeout
        want = set(request_ids)
        with self._results_cond:
            while not want.issubset(self.results):
                left = deadline - time.monotonic()
                if left <= 0 or self.alive_replicas == 0:
                    break
                self._results_cond.wait(min(left, 0.1))
            return {rid: self.results[rid]
                    for rid in want & set(self.results)}

    # ----------------------------------------------------------- exposition
    def stats(self) -> dict:
        from .engine import _m_latency, _m_ttft

        h = _m_latency.get()
        t = _m_ttft.get()
        return {
            "replicas": [e.stats() for e in self.engines],
            "alive_replicas": self.alive_replicas,
            "queue_depth": self.queue.depth,
            "completed": len(self.results),
            "evictions": list(self.evictions),
            "scale_events": list(self.scale_events),
            "latency_ms": {k: h[k] for k in ("count", "p50", "p95", "p99")},
            "ttft_ms": {k: t[k] for k in ("count", "p50", "p95", "p99")},
        }

"""Multi-replica dispatch: N engines behind one queue, with eviction.

The serving analog of PR-4's training fault model. Each replica is a
``ServingEngine`` driven by its own daemon worker thread; all replicas
share the decode model's parameter arrays zero-copy (``Predictor.clone``
semantics — per-replica state is only the KV pool + batch) and race for
work on one admission-controlled ``RequestQueue``.

Failure handling — a replica leaves the set, its work does not:

  hang     a per-replica ``robustness.watchdog.HangDetector`` beats once
           per scheduler tick; a step stuck past the timeout evicts the
           replica from the detector's poll thread.
  corrupt  a ``robustness.distributed_ft.ReplicaGuard`` (policy="raise")
           digests the replica's parameters every ``guard_every`` steps
           against the set's boot-time reference digest — the serving
           variant of the SDC check, with the reference playing the role
           of the agreeing peer.
  error    any exception escaping ``engine.step()``.

Eviction = ``engine.drain()`` (fences the zombie thread via the engine's
``alive`` flag — a stuck step that wakes later cannot commit results) +
fresh copies of every in-flight request re-admitted at the queue head
for the surviving replicas. An accepted request is therefore never lost
(``tests/test_serving.py`` chaos cases pin zero-lost under hang, crash,
and corruption).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..observability.events import get_event_log
from ..observability.metrics import get_registry as _get_registry
from ..observability.tracing import get_tracer as _get_tracer
from .engine import ReplicaBootBudgetExceeded, ServingEngine
from .kv_cache import KVBlockPool
from .model import GPTDecodeModel
from .scheduler import RequestQueue, ServeRequest

__all__ = ["ReplicaSet", "StandbyReplica"]

_m_evictions = _get_registry().counter(
    "serve_replica_evictions_total", "replicas evicted from the set",
    labels=("reason",))
_m_scale_events = _get_registry().counter(
    "serve_scale_events_total",
    "policy-driven replica scale events (fleet controller)",
    labels=("direction",))
_m_boots = _get_registry().counter(
    "replica_boots_total",
    "replica boots by mode (warm = standby pre-compiled every seen "
    "shape bucket before admission) and outcome",
    labels=("mode", "outcome"))
_m_boot_ms = _get_registry().histogram(
    "replica_boot_ms",
    "wall time from boot request to readiness (warm: standby warm + "
    "promote; cold: engine construction — compiles land in-traffic)",
    buckets=(1, 5, 10, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
             30000, 120000))


class StandbyReplica:
    """A replica acquired for warm handoff but NOT yet in the set.

    Lifecycle is a strict either/or, machine-checked by analysis rule
    F006: every CFG path from :meth:`ReplicaSet.acquire_standby` must
    either :meth:`promote` the standby into the set or tear it down
    (:meth:`abandon`/:meth:`stop`) — a dropped standby leaks its KV pool
    and, once promoted paths would have armed them, a worker thread +
    watchdog. ``warm()`` runs on the CALLER's thread (the outgoing
    replica keeps serving meanwhile); ``ready()`` is the readiness probe
    the replacement protocol requires before it fences the old replica.
    """

    def __init__(self, rset: "ReplicaSet", engine: ServingEngine,
                 model: GPTDecodeModel):
        self._set = rset
        self.engine = engine
        self.model = model
        self.promoted = False
        self.abandoned = False

    def warm(self, buckets, deadline: Optional[float] = None) -> int:
        """Pre-compile every bucket; raises ReplicaBootBudgetExceeded
        past ``deadline`` (see ServingEngine.warm)."""
        return self.engine.warm(buckets, deadline=deadline)

    def ready(self) -> bool:
        """The readiness probe: warmed, alive, and reporting "serving" —
        admitting traffic now cannot open a compile window."""
        return (self.engine.alive and self.engine._warm
                and self.engine.state == "serving")

    def promote(self, reason: str = "warm_handoff") -> int:
        """Swap into the set (worker + watchdog arm if the set runs).
        Returns the new replica index."""
        if self.abandoned:
            raise RuntimeError(f"{self.engine.name}: promote after abandon")
        idx = self._set._adopt(self, reason)
        self.promoted = True
        return idx

    def abandon(self):
        """Tear down an unpromoted standby: fence the engine so its pool
        can never admit work. Idempotent; a no-op after promote."""
        if not self.promoted:
            self.engine.alive = False
            self.abandoned = True

    # F006 accepts either teardown spelling; stop() is the ReplicaSet-
    # lifecycle-consistent alias
    stop = abandon


class ReplicaSet:
    """N serving replicas behind one request queue."""

    def __init__(self, model: GPTDecodeModel, n_replicas: int = 2,
                 queue: Optional[RequestQueue] = None,
                 n_blocks: int = 64, block_tokens: Optional[int] = None,
                 codec: Optional[str] = None,
                 max_batch: Optional[int] = None,
                 watchdog_timeout: Optional[float] = None,
                 guard_every: int = 0,
                 models: Optional[List[GPTDecodeModel]] = None,
                 pre_step_hooks: Optional[Dict[int, Callable]] = None,
                 prefix_cache: Optional[bool] = None,
                 draft_model: Optional[GPTDecodeModel] = None,
                 spec_k: Optional[int] = None,
                 sampler=None,
                 compile_grace: Optional[float] = None):
        from ..framework.flags import flag

        self.model = model
        # `is not None`, NOT truthiness: an EMPTY RequestQueue is falsy
        # (__len__ == 0), and `queue or ...` would silently replace the
        # caller's queue with a private one
        self.queue = queue if queue is not None else RequestQueue(
            max_depth=int(flag("FLAGS_serving_queue_depth", 256)))
        block_tokens = int(block_tokens
                           or flag("FLAGS_serving_block_tokens", 16))
        self.codec = codec or str(flag("FLAGS_serving_kv_codec", "fp32"))
        self.watchdog_timeout = float(
            watchdog_timeout or flag("FLAGS_serving_watchdog_s", 30.0))
        self.compile_grace = float(
            compile_grace if compile_grace is not None
            else flag("FLAGS_serving_compile_grace_s", 120.0))
        self.guard_every = int(guard_every)
        # kept for scale_up: a policy-grown replica gets the same pool
        # and batch geometry as the boot-time ones
        self._n_blocks = int(n_blocks)
        self._block_tokens = block_tokens
        self._max_batch = max_batch
        self._sampler = sampler
        self._prefix_cache = prefix_cache
        self._draft = draft_model
        self._spec_k = spec_k
        self._models = list(models) if models else [model] * n_replicas
        if len(self._models) != n_replicas:
            raise ValueError("models override must have one entry per "
                             "replica")
        self._hooks = dict(pre_step_hooks or {})
        self.engines: List[ServingEngine] = []
        for i in range(n_replicas):
            self.engines.append(self._new_engine(i, self._models[i]))
        self.results: Dict[str, ServeRequest] = {}
        self.evictions: List[dict] = []
        self.scale_events: List[dict] = []
        # boot ledger (ISSUE 19): one record per replica boot with mode
        # (warm|cold), outcome (ok|warm_boot_timeout) and wall-clock
        # window [t_start, t] — the chaos harness asserts no hang
        # eviction lands inside any boot window
        self.boots: List[dict] = []
        # monotonic name sequence: standbys may be abandoned without
        # joining the set, so names come from a counter, not len(engines)
        self._name_seq = n_replicas
        self._results_cond = threading.Condition()
        self._evict_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._hds: list = []
        self._ref_digest = None

    def _new_engine(self, idx: int, model: GPTDecodeModel) -> ServingEngine:
        pool = KVBlockPool(n_blocks=self._n_blocks,
                           block_tokens=self._block_tokens,
                           elems_per_token=model.elems_per_token,
                           codec=self.codec)
        # the draft model (like the target) is stateless jitted
        # params — shared zero-copy; per-replica draft state is only
        # the per-sequence dense mirrors inside the engine
        return ServingEngine(
            model, pool, self.queue, max_batch=self._max_batch,
            name=f"replica-{idx}", pre_step=self._hooks.get(idx),
            on_finish=self._on_finish, sampler=self._sampler,
            prefix_cache=self._prefix_cache, draft_model=self._draft,
            spec_k=self._spec_k)

    def _alloc_seq(self) -> int:
        s = self._name_seq
        self._name_seq += 1
        return s

    # ------------------------------------------------------------ lifecycle
    def _spawn_worker(self, idx: int):
        """Arm a compile-grace-aware watchdog + daemon worker for one
        engine (boot-time and scale_up share this path)."""
        from ..robustness.watchdog import HangDetector

        eng = self.engines[idx]
        # A warm-booted engine has already executed every known bucket:
        # its first poll needs NO compile grace (the PR-17 plumbing stays
        # only for genuinely cold paths — asserted in tests).
        grace = 0.0 if eng._warm else self.compile_grace
        hd = HangDetector(
            timeout=self.watchdog_timeout,
            on_hang=lambda age, i=idx: self.evict(i, "hang"),
            state_fn=lambda e=eng: e.state,
            compile_grace=grace)
        self._hds.append(hd)
        hd.start()
        t = threading.Thread(target=self._worker, args=(idx,),
                             daemon=True, name=f"serve-{eng.name}")
        self._threads.append(t)
        t.start()

    def start(self) -> "ReplicaSet":
        from ..observability import exposition
        from ..robustness.distributed_ft import params_digest

        if self._threads:
            return self
        if self.guard_every:
            self._ref_digest = params_digest(self.model.param_list())
        for i in range(len(self.engines)):
            self._spawn_worker(i)
        exposition.register_section("serving", self.stats)
        # /traces (index) + /traces/<id> (one request's full span list),
        # read-only over the bounded trace store, mounted for the set's
        # lifetime like /serving
        exposition.register_section(
            "traces", lambda: _get_tracer().store.index(),
            lambda tid: _get_tracer().store.get(tid))
        return self

    def stop(self):
        self._stop.set()
        self.queue.close()
        for hd in self._hds:
            hd.stop()
        for t in self._threads:
            t.join(timeout=5)
        from ..observability import exposition

        exposition.unregister_section("serving")
        exposition.unregister_section("traces")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- workers
    def _worker(self, idx: int):
        from ..robustness.distributed_ft import (
            ReplicaDivergenceError, ReplicaGuard,
        )

        eng = self.engines[idx]
        hd = self._hds[idx]
        guard = None
        if self.guard_every:
            ref = self._ref_digest

            def against_ref(digest):
                import numpy as np

                return (np.minimum(digest, ref), np.maximum(digest, ref))

            guard = ReplicaGuard(policy="raise", every_n=self.guard_every,
                                 reduce_fn=against_ref)
        while not self._stop.is_set() and eng.alive:
            try:
                if guard is not None:
                    guard.maybe_check(self._models[idx].param_list(),
                                      step=eng.steps)
                worked = eng.step()
            except ReplicaDivergenceError:
                self.evict(idx, "corrupt")
                return
            except Exception as e:  # any escaped step error evicts
                get_event_log().error("serving", "replica step failed",
                                      replica=eng.name, error=repr(e))
                self.evict(idx, "error")
                return
            hd.beat()
            if not worked:
                self.queue.wait_nonempty(0.02)

    # ------------------------------------------------------------- eviction
    def evict(self, idx: int, reason: str):
        """Remove a replica: fence it, drain its in-flight requests, and
        re-admit them at the queue head. Idempotent per replica."""
        eng = self.engines[idx]
        with self._evict_lock:
            if not eng.alive:
                return
            drained = eng.drain()
        tracer = _get_tracer()
        for r in drained:
            tracer.record_span(r.trace, "eviction", replica=eng.name,
                               reason=reason, attempt=r.attempts)
        # requeue FIRST — nothing below may stand between a drained
        # request and its re-admission. The detector is disarmed without
        # a join: eviction often runs ON its poll thread (on_hang).
        self.queue.requeue_front(drained)
        if idx < len(self._hds):
            self._hds[idx]._stop.set()
        _m_evictions.labels(reason=reason).inc()
        self.evictions.append({"replica": eng.name, "reason": reason,
                               "drained": len(drained),
                               "t": time.monotonic()})
        get_event_log().error(
            "serving", "replica evicted", replica=eng.name, reason=reason,
            drained=len(drained))

    # ------------------------------------------------------------- scaling
    # Policy-driven capacity changes (ISSUE 17 fleet controller). Scale
    # DOWN goes through the exact eviction mechanics — fence + drain +
    # requeue_front — so the zero-lost-requests guarantee is the same
    # machine-checked path (analysis rule F004), just with a "scale"
    # ledger entry instead of a failure reason.
    def scale_down(self, idx: Optional[int] = None,
                   reason: str = "scale_down") -> Optional[dict]:
        """Retire one replica without losing work. Defaults to the
        highest-index alive replica (deterministic for trace replay).
        Returns the scale-event record, or None if nothing was alive."""
        if idx is None:
            alive = [i for i, e in enumerate(self.engines) if e.alive]
            if not alive:
                return None
            idx = alive[-1]
        eng = self.engines[idx]
        with self._evict_lock:
            if not eng.alive:
                return None
            drained = eng.drain()
        tracer = _get_tracer()
        for r in drained:
            tracer.record_span(r.trace, "scale_down", replica=eng.name,
                               reason=reason, attempt=r.attempts)
        self.queue.requeue_front(drained)
        if idx < len(self._hds):
            self._hds[idx]._stop.set()
        _m_scale_events.labels(direction="down").inc()
        ev = {"replica": eng.name, "direction": "down", "reason": reason,
              "drained": len(drained), "t": time.monotonic()}
        self.scale_events.append(ev)
        get_event_log().info(
            "serving", "replica scaled down", replica=eng.name,
            reason=reason, drained=len(drained))
        return ev

    # -------------------------------------------- zero-cold-start plane
    def warm_buckets(self) -> set:
        """Union of every shape bucket any replica has executed — the
        set a standby must pre-compile to answer its readiness probe."""
        buckets: set = set()
        for e in self.engines:
            buckets |= e.seen_buckets()
        return buckets

    def acquire_standby(self, model: Optional[GPTDecodeModel] = None
                        ) -> StandbyReplica:
        """A fresh engine + KV pool OUTSIDE the set. Analysis rule F006
        requires every CFG path from here to promote or tear it down."""
        model = model if model is not None else self.model
        eng = self._new_engine(self._alloc_seq(), model)
        return StandbyReplica(self, eng, model)

    def _adopt(self, standby: StandbyReplica, reason: str) -> int:
        """Swap a ready standby into the set (StandbyReplica.promote)."""
        eng = standby.engine
        idx = len(self.engines)
        self.engines.append(eng)
        self._models.append(standby.model)
        if self._threads:  # live set: arm watchdog + worker like start()
            self._spawn_worker(idx)
        _m_scale_events.labels(direction="up").inc()
        ev = {"replica": eng.name, "direction": "up", "reason": reason,
              "drained": 0, "warm": True, "t": time.monotonic()}
        self.scale_events.append(ev)
        get_event_log().info(
            "serving", "standby promoted", replica=eng.name,
            reason=reason, replicas=self.alive_replicas)
        return idx

    def _record_boot(self, name: str, mode: str, outcome: str,
                     ms: float, t_start: float) -> dict:
        _m_boots.labels(mode=mode, outcome=outcome).inc()
        _m_boot_ms.observe(ms)
        rec = {"replica": name, "mode": mode, "outcome": outcome,
               "ms": round(ms, 3), "t_start": t_start,
               "t": time.monotonic()}
        self.boots.append(rec)
        return rec

    @property
    def last_boot(self) -> Optional[dict]:
        return self.boots[-1] if self.boots else None

    def warm_boot_counts(self) -> dict:
        """Cumulative boot outcomes — the fleet SignalsAdapter duck-reads
        this to stamp warm-boot fields onto FleetSignals."""
        return {
            "warm_boots": sum(1 for b in self.boots
                              if b["mode"] == "warm"
                              and b["outcome"] == "ok"),
            "warm_boot_timeouts": sum(1 for b in self.boots
                                      if b["outcome"]
                                      == "warm_boot_timeout"),
        }

    def scale_up(self, model: Optional[GPTDecodeModel] = None,
                 reason: str = "scale_up", warm: bool = False) -> int:
        """Boot one more replica (fresh engine + KV pool; weights shared
        zero-copy).

        ``warm=False`` (cold): the replica joins immediately and reports
        ``compiling`` on its first step — the watchdog's extended
        first-poll deadline (compile_grace) covers its in-traffic cold
        compile. ``warm=True``: a standby pre-compiles every bucket the
        set has executed, under ``FLAGS_replica_boot_budget_s``, and only
        joins once its readiness probe answers — no compile window, no
        grace needed. Past the budget the standby is abandoned, a
        ``warm_boot_timeout`` outcome is recorded, and the boot falls
        back to the cold path rather than hanging the fleet.

        Returns the new replica index."""
        from ..framework.flags import flag

        model = model if model is not None else self.model
        if warm:
            t0 = time.monotonic()
            budget = float(flag("FLAGS_replica_boot_budget_s", 300.0))
            standby = self.acquire_standby(model)
            ok = False
            try:
                standby.warm(self.warm_buckets(), deadline=t0 + budget)
                ok = standby.ready()
            except ReplicaBootBudgetExceeded:
                ok = False
            except BaseException:
                standby.abandon()  # unexpected failure: never leak it
                raise
            ms = (time.monotonic() - t0) * 1e3
            if ok:
                idx = standby.promote(reason)
                self._record_boot(self.engines[idx].name, "warm", "ok",
                                  ms, t0)
                return idx
            standby.abandon()
            self._record_boot(standby.engine.name, "warm",
                              "warm_boot_timeout", ms, t0)
            get_event_log().error(
                "serving", "warm boot budget exceeded — cold fallback",
                budget_s=budget, reason=reason)
            # fall through: capacity still arrives, compiling in-traffic
            # under compile_grace (the genuinely cold path the PR-17
            # plumbing remains for)
        t0 = time.monotonic()
        eng = self._new_engine(self._alloc_seq(), model)
        idx = len(self.engines)
        self.engines.append(eng)
        self._models.append(model)
        if self._threads:  # live set: arm watchdog + worker like start()
            self._spawn_worker(idx)
        _m_scale_events.labels(direction="up").inc()
        ev = {"replica": eng.name, "direction": "up",
              "reason": reason, "drained": 0, "t": time.monotonic()}
        self.scale_events.append(ev)
        self._record_boot(eng.name, "cold", "ok",
                          (time.monotonic() - t0) * 1e3, t0)
        get_event_log().info(
            "serving", "replica scaled up", replica=eng.name,
            reason=reason, replicas=self.alive_replicas)
        return idx

    def replace(self, idx: Optional[int] = None,
                reason: str = "warm_handoff") -> Optional[dict]:
        """Warm-handoff replacement (the zero-cold-start eviction): the
        standby boots and answers its readiness probe BEFORE the
        outgoing replica is fenced, so fence→drain→requeue never exposes
        a compile window to traffic. Past the boot budget the
        replacement arrives cold (recorded as such) and the handoff
        still completes. Defaults to the highest-index alive replica
        (deterministic, matching scale_down)."""
        if idx is None:
            alive = [i for i, e in enumerate(self.engines) if e.alive]
            if not alive:
                return None
            idx = alive[-1]
        old = self.engines[idx]
        if not old.alive:
            return None
        new_idx = self.scale_up(model=self._models[idx], reason=reason,
                                warm=True)
        boot = self.last_boot or {}
        with self._evict_lock:
            if not old.alive:
                return None
            drained = old.drain()
        tracer = _get_tracer()
        for r in drained:
            tracer.record_span(r.trace, "warm_handoff", replica=old.name,
                               standby=self.engines[new_idx].name,
                               reason=reason, boot_mode=boot.get("mode"),
                               boot_ms=boot.get("ms"),
                               attempt=r.attempts)
        self.queue.requeue_front(drained)
        if idx < len(self._hds):
            self._hds[idx]._stop.set()
        _m_scale_events.labels(direction="down").inc()
        ev = {"replica": old.name, "direction": "down", "reason": reason,
              "drained": len(drained),
              "standby": self.engines[new_idx].name,
              "boot_mode": boot.get("mode"), "t": time.monotonic()}
        self.scale_events.append(ev)
        get_event_log().info(
            "serving", "replica replaced (warm handoff)",
            replica=old.name, standby=self.engines[new_idx].name,
            reason=reason, drained=len(drained),
            boot_mode=boot.get("mode"))
        return ev

    def pump(self, ticks: int = 1) -> int:
        """Synchronous driving mode: step every alive engine in index
        order, no worker threads. Deterministic harnesses (the fleet
        chaos phase) drive the set from a trace clock through this
        instead of ``start()``; both modes share admit/decode/drain
        mechanics. Returns how many engine steps did work."""
        worked = 0
        for _ in range(int(ticks)):
            for eng in self.engines:
                if eng.alive and eng.step():
                    worked += 1
        return worked

    @property
    def alive_replicas(self) -> int:
        return sum(1 for e in self.engines if e.alive)

    def heartbeat_ages(self) -> List[float]:
        """Seconds since each armed watchdog last saw its replica beat
        (disarmed/evicted detectors excluded). The fleet SignalsAdapter
        reads the max as an early-warning hang signal — a replica whose
        age approaches the watchdog timeout is about to be evicted."""
        import time

        now = time.monotonic()
        return [now - hd._last for hd in self._hds
                if not hd._stop.is_set()]

    # -------------------------------------------------------------- serving
    def submit(self, req: ServeRequest) -> bool:
        return self.queue.submit(req)

    def _on_finish(self, engine: ServingEngine, req: ServeRequest):
        with self._results_cond:
            self.results[req.request_id] = req
            self._results_cond.notify_all()

    def wait(self, request_ids, timeout: float = 60.0
             ) -> Dict[str, ServeRequest]:
        """Block until every id has a terminal result (or timeout);
        returns the results seen so far either way."""
        import time

        deadline = time.monotonic() + timeout
        want = set(request_ids)
        with self._results_cond:
            while not want.issubset(self.results):
                left = deadline - time.monotonic()
                if left <= 0 or self.alive_replicas == 0:
                    break
                self._results_cond.wait(min(left, 0.1))
            return {rid: self.results[rid]
                    for rid in want & set(self.results)}

    # ----------------------------------------------------------- exposition
    def stats(self) -> dict:
        from .engine import _m_latency, _m_ttft

        h = _m_latency.get()
        t = _m_ttft.get()
        return {
            "replicas": [e.stats() for e in self.engines],
            "alive_replicas": self.alive_replicas,
            "queue_depth": self.queue.depth,
            "completed": len(self.results),
            "evictions": list(self.evictions),
            "scale_events": list(self.scale_events),
            "boots": list(self.boots),
            "latency_ms": {k: h[k] for k in ("count", "p50", "p95", "p99")},
            "ttft_ms": {k: t[k] for k in ("count", "p50", "p95", "p99")},
        }

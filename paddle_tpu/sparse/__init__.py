"""paddle.sparse — COO/CSR sparse tensors.

Parity: pten/core/sparse_coo_tensor.h:38, sparse_csr_tensor.h and the later
paddle.sparse API (sparse_coo_tensor/sparse_csr_tensor/to_dense/to_sparse_coo,
sparse matmul/add/relu). TPU-native backing: jax.experimental.sparse BCOO —
XLA lowers its matmuls to gather+MXU contractions; TPUs have no sparse unit,
so dense-off-ramp (`to_dense`) is the fast path for small densities.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.autograd import call_op as op
from ..framework.tensor import Tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "matmul", "add", "relu", "nnz",
]


class SparseCooTensor:
    """COO sparse tensor (indices [ndim, nnz] + values [nnz])."""

    def __init__(self, bcoo, shape):
        self._bcoo = bcoo
        self._shape = tuple(int(s) for s in shape)

    # -- paddle surface -----------------------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(self._bcoo.indices.T, _internal=True)

    def values(self):
        return Tensor(self._bcoo.data, _internal=True)

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), _internal=True)

    def to_sparse_csr(self):
        if len(self._shape) != 2:
            raise ValueError("CSR requires a 2-D tensor")
        dense = self._bcoo.todense()
        return _dense_to_csr(dense)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates(), self._shape)

    @property
    def dtype(self):
        return self._bcoo.data.dtype

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (crows/cols/values)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(np.asarray(crows), jnp.int32)
        self._cols = jnp.asarray(np.asarray(cols), jnp.int32)
        self._values = jnp.asarray(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return Tensor(self._crows, _internal=True)

    def cols(self):
        return Tensor(self._cols, _internal=True)

    def values(self):
        return Tensor(self._values, _internal=True)

    def nnz(self):
        return int(self._values.shape[0])

    def to_dense(self):
        n_rows = self._shape[0]
        counts = jnp.diff(self._crows)
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz())
        dense = jnp.zeros(self._shape, self._values.dtype)
        dense = dense.at[rows, self._cols].add(self._values)
        return Tensor(dense, _internal=True)

    def to_sparse_coo(self, sparse_dim=2):
        counts = jnp.diff(self._crows)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.nnz())
        idx = jnp.stack([rows, self._cols], axis=1)
        bcoo = jsparse.BCOO((self._values, idx), shape=self._shape)
        return SparseCooTensor(bcoo, self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(_val(indices), jnp.int32)  # (ndim, nnz) paddle layout
    vals = _val(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = _val(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(_val(crows), _val(cols), vals, shape)


def _dense_to_csr(dense):
    d = np.asarray(dense)
    nz = np.nonzero(d)
    rows, cols = nz[0], nz[1]
    vals = d[nz]
    crows = np.zeros(d.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, vals, d.shape)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def nnz(x):
    return x.nnz()


def matmul(x, y, name=None):
    """Sparse @ dense (reference: paddle.sparse.matmul)."""
    yv = _val(y)
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ yv
        return Tensor(out, _internal=True)
    if isinstance(x, SparseCsrTensor):
        return Tensor(_val(x.to_dense()) @ yv, _internal=True)
    raise TypeError("matmul expects a sparse lhs")


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = x._bcoo.todense() + y._bcoo.todense()
        return _dense_to_coo(out)
    raise TypeError("add expects two SparseCooTensors")


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        bcoo = jsparse.BCOO((jax.nn.relu(x._bcoo.data), x._bcoo.indices),
                            shape=x._bcoo.shape)
        return SparseCooTensor(bcoo, x._shape)
    raise TypeError("relu expects a SparseCooTensor")


def _dense_to_coo(dense):
    d = np.asarray(dense)
    nz = np.nonzero(d)
    idx = np.stack(nz, axis=0)
    return sparse_coo_tensor(idx, d[nz], d.shape)


# Tensor method: dense → sparse (paddle Tensor.to_sparse_coo)
def _tensor_to_sparse_coo(self, sparse_dim=None):
    return _dense_to_coo(self.numpy())


Tensor.to_sparse_coo = _tensor_to_sparse_coo

"""paddle_tpu.core — native (C++) runtime components.

The reference keeps its PS tables, data feed, and executor internals in C++
(SURVEY.md §2.1/§2.4); here the host-side hot paths with no XLA analog are
C++ too: the memory sparse table and the blocking data queue. Built on first
use with g++ (no pybind11 in this image — plain C ABI + ctypes).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csrc")
_LIBDIR = os.path.join(_HERE, "_lib")
_lock = threading.Lock()
_lib = None

_SOURCES = ["sparse_table.cc", "blocking_queue.cc"]


def _build():
    os.makedirs(_LIBDIR, exist_ok=True)
    so_path = os.path.join(_LIBDIR, "libpaddle_tpu_core.so")
    srcs = [os.path.join(_SRC, s) for s in _SOURCES]
    stamp = os.path.join(_LIBDIR, ".stamp")
    newest = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(so_path) and os.path.exists(stamp) and \
            os.path.getmtime(stamp) >= newest:
        return so_path
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", so_path, *srcs]
    subprocess.run(cmd, check=True, capture_output=True)
    with open(stamp, "w") as f:
        f.write("ok")
    return so_path


def load_library():
    """Compile (if stale) and dlopen the native core."""
    global _lib
    with _lock:
        if _lib is None:
            so = _build()
            lib = ctypes.CDLL(so)
            _configure(lib)
            _lib = lib
    return _lib


def _configure(lib):
    c = ctypes
    u64p = c.POINTER(c.c_uint64)
    f32p = c.POINTER(c.c_float)
    u8p = c.POINTER(c.c_uint8)

    lib.pt_sparse_table_create.restype = c.c_void_p
    lib.pt_sparse_table_create.argtypes = [
        c.c_int, c.c_int, c.c_int, c.c_float, c.c_float, c.c_float,
        c.c_uint64]
    lib.pt_sparse_table_destroy.argtypes = [c.c_void_p]
    lib.pt_sparse_table_dim.argtypes = [c.c_void_p]
    lib.pt_sparse_table_dim.restype = c.c_int
    lib.pt_sparse_table_size.argtypes = [c.c_void_p]
    lib.pt_sparse_table_size.restype = c.c_uint64
    lib.pt_sparse_table_pull.argtypes = [c.c_void_p, u64p, c.c_int64, f32p,
                                         c.c_int]
    lib.pt_sparse_table_push.argtypes = [c.c_void_p, u64p, c.c_int64, f32p,
                                         c.c_float]
    lib.pt_sparse_table_assign.argtypes = [c.c_void_p, u64p, c.c_int64, f32p]
    lib.pt_sparse_table_add.argtypes = [c.c_void_p, u64p, c.c_int64, f32p]
    lib.pt_sparse_table_keys.argtypes = [c.c_void_p, u64p, c.c_int64]
    lib.pt_sparse_table_keys.restype = c.c_int64
    lib.pt_sparse_table_shrink.argtypes = [c.c_void_p, c.c_float, c.c_float]
    lib.pt_sparse_table_shrink.restype = c.c_int64
    lib.pt_sparse_table_add_show.argtypes = [c.c_void_p, u64p, c.c_int64,
                                             c.c_float]
    lib.pt_sparse_table_save.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_sparse_table_save.restype = c.c_int
    lib.pt_sparse_table_load.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_sparse_table_load.restype = c.c_int
    lib.pt_sparse_table_enable_ssd.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_sparse_table_enable_ssd.restype = c.c_int
    lib.pt_sparse_table_spill.argtypes = [c.c_void_p, c.c_int64]
    lib.pt_sparse_table_spill.restype = c.c_int64
    lib.pt_sparse_table_ssd_compact.argtypes = [c.c_void_p]
    lib.pt_sparse_table_ssd_compact.restype = c.c_int64
    lib.pt_sparse_table_ssd_rows.argtypes = [c.c_void_p]
    lib.pt_sparse_table_ssd_rows.restype = c.c_int64
    lib.pt_sparse_table_mem_rows.argtypes = [c.c_void_p]
    lib.pt_sparse_table_mem_rows.restype = c.c_uint64

    lib.pt_queue_create.restype = c.c_void_p
    lib.pt_queue_create.argtypes = [c.c_uint64]
    lib.pt_queue_destroy.argtypes = [c.c_void_p]
    lib.pt_queue_push.argtypes = [c.c_void_p, u8p, c.c_uint64, c.c_int]
    lib.pt_queue_push.restype = c.c_int
    lib.pt_queue_pop_size.argtypes = [c.c_void_p, c.c_int]
    lib.pt_queue_pop_size.restype = c.c_int64
    lib.pt_queue_pop.argtypes = [c.c_void_p, u8p, c.c_uint64]
    lib.pt_queue_pop.restype = c.c_int64
    lib.pt_queue_close.argtypes = [c.c_void_p]
    lib.pt_queue_size.argtypes = [c.c_void_p]
    lib.pt_queue_size.restype = c.c_uint64
    lib.pt_queue_is_closed.argtypes = [c.c_void_p]
    lib.pt_queue_is_closed.restype = c.c_int

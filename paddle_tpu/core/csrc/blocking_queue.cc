// Bounded MPMC byte-buffer queue — the data-feed decoupling primitive.
// TPU-native counterpart of the reference's LoDTensorBlockingQueue
// (paddle/fluid/operators/reader/lod_tensor_blocking_queue.h) + the
// BlockingQueue under it: producer workers (host preprocessing) hand
// serialized batches to the consumer (device feed) without the GIL.
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> data;
};

struct Queue {
  size_t capacity;
  std::deque<Buffer> items;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  bool closed = false;
  uint64_t pushed = 0, popped = 0;

  explicit Queue(size_t cap) : capacity(cap) {}
};

}  // namespace

extern "C" {

void* pt_queue_create(uint64_t capacity) {
  return new Queue(capacity ? capacity : 1);
}

void pt_queue_destroy(void* q) { delete static_cast<Queue*>(q); }

// 0 = ok, -1 = closed
int pt_queue_push(void* qp, const uint8_t* data, uint64_t len,
                  int timeout_ms) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -2;  // timeout
  }
  if (q->closed) return -1;
  Buffer b;
  b.data.assign(data, data + len);
  q->items.push_back(std::move(b));
  ++q->pushed;
  q->not_empty.notify_one();
  return 0;
}

// Returns length (>0), 0 if closed-and-drained, -2 on timeout.
// Two-phase: peek length, then copy out (caller allocates).
int64_t pt_queue_pop_size(void* qp, int timeout_ms) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -2;
  }
  if (q->items.empty()) return 0;  // closed + drained
  return static_cast<int64_t>(q->items.front().data.size());
}

int64_t pt_queue_pop(void* qp, uint8_t* out, uint64_t cap) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  if (q->items.empty()) return 0;
  Buffer& b = q->items.front();
  if (b.data.size() > cap) return -3;
  std::memcpy(out, b.data.data(), b.data.size());
  int64_t n = static_cast<int64_t>(b.data.size());
  q->items.pop_front();
  ++q->popped;
  q->not_full.notify_one();
  return n;
}

void pt_queue_close(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> g(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

uint64_t pt_queue_size(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> g(q->mu);
  return q->items.size();
}

int pt_queue_is_closed(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> g(q->mu);
  return q->closed ? 1 : 0;
}

}  // extern "C"

// Memory sparse table — the host-resident embedding store of the PS
// subsystem. TPU-native counterpart of the reference's C++
// MemorySparseTable (paddle/fluid/distributed/ps/table/memory_sparse_table.cc)
// + SparseSgdRule accessors (ps/table/sparse_sgd_rule.cc): sharded hash maps
// with striped locks, lazily-initialized rows, and fused pull/push kernels so
// the hot path (CTR-scale embedding lookup/update) never touches Python.
//
// Exposed as a C ABI for ctypes binding (no pybind11 in this image).
//
// SSD tier (reference: ps/table/ssd_sparse_table.cc over rocksdb): a
// log-structured spill file + in-memory offset index. pt_sparse_table_spill
// evicts the coldest rows (oldest push version) past a row budget to disk;
// pull/push transparently fault disk-resident rows back into memory. The
// index costs ~16 bytes/key vs (2*dim*4 + overhead) for a resident row, so
// CTR-scale vocabularies fit host RAM + disk.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <unistd.h>  // pread: thread-safe positioned reads of the spill log

namespace {

struct Row {
  std::vector<float> emb;    // embedding weights [dim]
  std::vector<float> state;  // optimizer slot (adagrad G / momentum) [dim]
  // Bumped on EVERY mutation (push, add, assign, add_show, load) — not just
  // push. The two-pass spill's re-verification relies on this: a mutator
  // that skips the bump lets spill publish its pre-mutation snapshot and
  // erase the memory copy, silently undoing the mutation. Also the
  // geo-sync watermark.
  uint64_t version = 0;
  float show = 0.f;          // CTR accessor statistics
  float click = 0.f;
};

struct Shard {
  std::unordered_map<uint64_t, Row> map;
  std::mutex mu;
};

// Log-structured disk tier: records appended as
// [key u64][version u64][show f32][click f32][emb f32*dim][state f32*dim];
// the in-memory index maps key -> latest record offset (older records
// become garbage; pt_sparse_table_ssd_compact rewrites the log).
struct DiskTier {
  FILE* f = nullptr;
  std::string path;
  std::unordered_map<uint64_t, uint64_t> index;
  // shared: concurrent pread faults (the CTR pull-storm hot path);
  // exclusive: appends, index mutation, compaction's file swap
  std::shared_mutex mu;

  ~DiskTier() {
    if (f) std::fclose(f);
  }
};

enum class Optimizer : int { kSGD = 0, kAdagrad = 1, kMomentum = 2 };

struct Table {
  int dim;
  int shard_bits;
  Optimizer opt;
  float init_range;
  float lr_default;
  float momentum_or_eps;  // momentum coeff / adagrad epsilon
  std::vector<Shard> shards;
  std::atomic<uint64_t> global_version{0};
  uint64_t seed;
  std::unique_ptr<DiskTier> ssd;  // optional overflow tier
  // serializes the cross-tier maintenance ops (spill/compact/save/shrink):
  // their mem-key snapshots are only consistent if no concurrent spill can
  // move rows between tiers mid-operation. Never held while a shard or
  // tier mutex is already held (maint -> shard -> tier lock order).
  std::mutex maint_mu;

  Table(int d, int bits, int opt_kind, float init, float lr, float aux,
        uint64_t seed_)
      : dim(d),
        shard_bits(bits),
        opt(static_cast<Optimizer>(opt_kind)),
        init_range(init),
        lr_default(lr),
        momentum_or_eps(aux),
        shards(size_t(1) << bits),
        seed(seed_) {}

  inline Shard& shard_of(uint64_t key) {
    if (shard_bits == 0) return shards[0];
    // multiplicative hash → top bits pick the shard
    uint64_t h = key * 0x9E3779B97F4A7C15ull;
    return shards[h >> (64 - shard_bits)];
  }

  void init_row(Row& row, uint64_t key) {
    row.emb.resize(dim);
    row.state.assign(dim, 0.f);
    // deterministic in (key, table seed) only — identical across ranks and
    // restarts regardless of materialization order
    uint64_t h = (key ^ seed) * 0x9E3779B97F4A7C15ull;
    h ^= h >> 29;
    std::mt19937 gen(static_cast<uint32_t>(h ^ (h >> 32)));
    std::uniform_real_distribution<float> dist(-init_range, init_range);
    for (int i = 0; i < dim; ++i) row.emb[i] = dist(gen);
  }

  // Lock order everywhere: shard.mu THEN ssd->mu (never the reverse).

  // record header: [key u64][version u64][show f32][click f32]
  static constexpr size_t kHeadBytes = 8 + 8 + 4 + 4;
  size_t rec_bytes() const { return kHeadBytes + 2 * sizeof(float) * dim; }

  // Append one record WITHOUT flushing or publishing (caller holds
  // ssd->mu exclusive). The offset is only safe to publish in the index
  // AFTER an fflush — pread readers bypass the stdio buffer. On a short
  // write the log tail is garbage but unreferenced.
  bool ssd_append_raw_locked(uint64_t key, const Row& row, uint64_t* off) {
    if (!ssd->f) return false;
    std::fseek(ssd->f, 0, SEEK_END);
    *off = static_cast<uint64_t>(std::ftell(ssd->f));
    size_t ok = 0;
    ok += std::fwrite(&key, 8, 1, ssd->f);
    ok += std::fwrite(&row.version, 8, 1, ssd->f);
    ok += std::fwrite(&row.show, 4, 1, ssd->f);
    ok += std::fwrite(&row.click, 4, 1, ssd->f);
    ok += (std::fwrite(row.emb.data(), sizeof(float), dim, ssd->f) ==
           static_cast<size_t>(dim));
    ok += (std::fwrite(row.state.data(), sizeof(float), dim, ssd->f) ==
           static_cast<size_t>(dim));
    return ok == 6;
  }

  bool ssd_append_locked(uint64_t key, const Row& row) {
    // single-record append + flush + publish (callers that batch use
    // ssd_append_raw_locked and flush once)
    uint64_t off;
    if (!ssd_append_raw_locked(key, row, &off)) return false;
    if (std::fflush(ssd->f) != 0) return false;
    ssd->index[key] = off;
    return true;
  }

  bool ssd_read_locked(uint64_t key, Row& out) {
    // caller holds ssd->mu EXCLUSIVE (maintenance paths: shrink/save/
    // compact iterate the index and may interleave appends)
    if (!ssd->f) return false;
    auto it = ssd->index.find(key);
    if (it == ssd->index.end()) return false;
    std::fflush(ssd->f);
    std::fseek(ssd->f, static_cast<long>(it->second), SEEK_SET);
    uint64_t k2 = 0;
    out.emb.resize(dim);
    out.state.resize(dim);
    if (std::fread(&k2, 8, 1, ssd->f) != 1 || k2 != key ||
        std::fread(&out.version, 8, 1, ssd->f) != 1 ||
        std::fread(&out.show, 4, 1, ssd->f) != 1 ||
        std::fread(&out.click, 4, 1, ssd->f) != 1 ||
        std::fread(out.emb.data(), sizeof(float), dim, ssd->f) !=
            static_cast<size_t>(dim) ||
        std::fread(out.state.data(), sizeof(float), dim, ssd->f) !=
            static_cast<size_t>(dim)) {
      return false;
    }
    return true;
  }

  bool ssd_read_shared(uint64_t key, Row& out, uint64_t* off_out,
                       bool with_payload = true) {
    // Concurrent fault path: index lookup + pread under a SHARED lock.
    // pread needs no seek (no FILE* position races) and the exclusive
    // lock taken by compaction's file swap keeps the fd valid for the
    // read's duration. Appends fflush before publishing their index
    // entry, so a published offset always has its bytes in the kernel.
    if (!ssd) return false;
    std::shared_lock<std::shared_mutex> g(ssd->mu);
    if (!ssd->f) return false;
    auto it = ssd->index.find(key);
    if (it == ssd->index.end()) return false;
    *off_out = it->second;
    const int fd = ::fileno(ssd->f);
    const off_t base = static_cast<off_t>(it->second);
    // header to the stack, payloads straight into the row's buffers — no
    // per-fault heap allocation on the pull-storm hot path
    char head[kHeadBytes];
    if (::pread(fd, head, sizeof(head), base) !=
        static_cast<ssize_t>(sizeof(head)))
      return false;
    uint64_t k2;
    std::memcpy(&k2, head, 8);
    if (k2 != key) return false;
    std::memcpy(&out.version, head + 8, 8);
    std::memcpy(&out.show, head + 16, 4);
    std::memcpy(&out.click, head + 20, 4);
    if (!with_payload) return true;  // caller will overwrite emb/state
    out.emb.resize(dim);
    out.state.resize(dim);
    const ssize_t payload = static_cast<ssize_t>(sizeof(float)) * dim;
    if (::pread(fd, out.emb.data(), payload, base + kHeadBytes) != payload ||
        ::pread(fd, out.state.data(), payload,
                base + kHeadBytes + payload) != payload)
      return false;
    return true;
  }

  // Fault a disk-resident row into `s.map` (caller holds s.mu). Returns the
  // iterator, or map.end() when the key lives on neither tier. The disk
  // record is dropped from the index: leaving it would let a later shrink
  // of the memory copy resurrect the stale pre-spill row.
  // with_payload=false skips the emb/state preads (header stats only) for
  // callers about to overwrite both, e.g. checkpoint load; the rare
  // moved-offset fallback below still reads fully, which is harmless.
  std::unordered_map<uint64_t, Row>::iterator fault_in(
      Shard& s, uint64_t key, bool with_payload = true) {
    if (!ssd) return s.map.end();
    Row row;
    uint64_t off;
    // read under the SHARED lock (concurrent with other shards' faults).
    // spill/assign writers of THIS key take s.mu first (which we hold),
    // but shrink's disk phase rewrites/drops records under ssd->mu alone
    // — so before consuming the copy, re-validate the offset under the
    // exclusive lock and re-read (or give up) if it moved.
    if (!ssd_read_shared(key, row, &off, with_payload)) return s.map.end();
    {
      std::lock_guard<std::shared_mutex> g(ssd->mu);
      auto it = ssd->index.find(key);
      if (it == ssd->index.end()) return s.map.end();  // shrink evicted it
      if (it->second != off && !ssd_read_locked(key, row))
        return s.map.end();  // rewritten (decayed stats): take the new copy
      ssd->index.erase(key);
    }
    return s.map.emplace(key, std::move(row)).first;
  }
};

}  // namespace

extern "C" {

void* pt_sparse_table_create(int dim, int shard_bits, int opt_kind,
                             float init_range, float lr, float aux,
                             uint64_t seed) {
  if (shard_bits < 0 || shard_bits > 16 || dim <= 0) return nullptr;
  return new Table(dim, shard_bits, opt_kind, init_range, lr, aux, seed);
}

void pt_sparse_table_destroy(void* t) { delete static_cast<Table*>(t); }

int pt_sparse_table_dim(void* t) { return static_cast<Table*>(t)->dim; }

static std::unordered_set<uint64_t> mem_key_snapshot(Table* tab) {
  std::unordered_set<uint64_t> mem;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) mem.insert(kv.first);
  }
  return mem;
}

uint64_t pt_sparse_table_size(void* t) {
  auto* tab = static_cast<Table*>(t);
  if (!tab->ssd) {  // common case: cheap per-shard sum, no key walk
    uint64_t n = 0;
    for (auto& s : tab->shards) {
      std::lock_guard<std::mutex> g(s.mu);
      n += s.map.size();
    }
    return n;
  }
  // union of the memory tier and disk-only keys (an assigned row may exist
  // on both tiers; the memory copy is authoritative)
  auto mem = mem_key_snapshot(tab);
  uint64_t n = mem.size();
  std::shared_lock<std::shared_mutex> g(tab->ssd->mu);
  for (auto& kv : tab->ssd->index)
    if (!mem.count(kv.first)) ++n;
  return n;
}

uint64_t pt_sparse_table_mem_rows(void* t) {
  auto* tab = static_cast<Table*>(t);
  uint64_t n = 0;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    n += s.map.size();
  }
  return n;
}

// Pull rows for n keys into out[n * dim]; missing keys are initialized
// (create_if_missing != 0) or zero-filled.
void pt_sparse_table_pull(void* t, const uint64_t* keys, int64_t n,
                          float* out, int create_if_missing) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it == s.map.end()) it = tab->fault_in(s, keys[i]);
    if (it == s.map.end()) {
      if (!create_if_missing) {
        std::memset(out + i * dim, 0, sizeof(float) * dim);
        continue;
      }
      it = s.map.emplace(keys[i], Row{}).first;
      tab->init_row(it->second, keys[i]);
    }
    std::memcpy(out + i * dim, it->second.emb.data(), sizeof(float) * dim);
  }
}

// Apply gradients for n keys (duplicate keys fold sequentially — downpour
// semantics). lr<=0 uses the table default.
void pt_sparse_table_push(void* t, const uint64_t* keys, int64_t n,
                          const float* grads, float lr) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  const float eta = lr > 0.f ? lr : tab->lr_default;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it == s.map.end()) it = tab->fault_in(s, keys[i]);
    if (it == s.map.end()) {
      it = s.map.emplace(keys[i], Row{}).first;
      tab->init_row(it->second, keys[i]);
    }
    Row& row = it->second;
    const float* gi = grads + i * dim;
    switch (tab->opt) {
      case Optimizer::kSGD:
        for (int d = 0; d < dim; ++d) row.emb[d] -= eta * gi[d];
        break;
      case Optimizer::kAdagrad:
        for (int d = 0; d < dim; ++d) {
          row.state[d] += gi[d] * gi[d];
          row.emb[d] -=
              eta * gi[d] / (std::sqrt(row.state[d]) + tab->momentum_or_eps);
        }
        break;
      case Optimizer::kMomentum:
        for (int d = 0; d < dim; ++d) {
          row.state[d] = tab->momentum_or_eps * row.state[d] + gi[d];
          row.emb[d] -= eta * row.state[d];
        }
        break;
    }
    row.version = ++tab->global_version;
  }
}

// Atomically add deltas to rows (geo-SGD server-side merge,
// geo_recorder/communicator delta semantics): unlike a client-side
// pull+assign, concurrent workers' deltas can never lose updates.
void pt_sparse_table_add(void* t, const uint64_t* keys, int64_t n,
                         const float* deltas) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it == s.map.end()) it = tab->fault_in(s, keys[i]);
    if (it == s.map.end()) {
      it = s.map.emplace(keys[i], Row{}).first;
      tab->init_row(it->second, keys[i]);
    }
    Row& row = it->second;
    const float* di = deltas + i * dim;
    for (int d = 0; d < dim; ++d) row.emb[d] += di[d];
    row.version = ++tab->global_version;
  }
}

// Overwrite rows (used by load / broadcast init).
void pt_sparse_table_assign(void* t, const uint64_t* keys, int64_t n,
                            const float* vals) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    // fault a spilled row into memory before overwriting so its show/click
    // stats survive the assign exactly like a memory-resident row's do
    // (fault_in also erases the disk record, so no stale copy remains)
    if (it == s.map.end()) it = tab->fault_in(s, keys[i]);
    if (it == s.map.end()) it = s.map.emplace(keys[i], Row{}).first;
    Row& row = it->second;
    if (row.emb.empty()) {
      row.emb.resize(dim);
      row.state.assign(dim, 0.f);
    }
    std::memcpy(row.emb.data(), vals + i * dim, sizeof(float) * dim);
    // bump version on EVERY mutation (not just push): the two-pass
    // spill's re-verification uses it to detect rows touched between its
    // snapshot append and its erase — an assign that didn't bump would
    // be silently undone by the spill publishing the pre-assign record
    row.version = ++tab->global_version;
    if (tab->ssd) {
      // same hazard fault_in guards against: a stale disk record would
      // resurrect the pre-assign row after a memory-tier shrink
      std::lock_guard<std::shared_mutex> g2(tab->ssd->mu);
      tab->ssd->index.erase(keys[i]);
    }
  }
}

// Snapshot keys (both tiers) into out_keys (caller allocates via size()).
int64_t pt_sparse_table_keys(void* t, uint64_t* out_keys, int64_t cap) {
  auto* tab = static_cast<Table*>(t);
  int64_t n = 0;
  std::unordered_set<uint64_t> seen;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) {
      if (n >= cap) return n;
      out_keys[n++] = kv.first;
      if (tab->ssd) seen.insert(kv.first);
    }
  }
  if (tab->ssd) {
    std::shared_lock<std::shared_mutex> g(tab->ssd->mu);
    for (auto& kv : tab->ssd->index) {
      if (seen.count(kv.first)) continue;
      if (n >= cap) return n;
      out_keys[n++] = kv.first;
    }
  }
  return n;
}

// Drop rows whose show-count decays below `threshold` (table shrink).
// Accessor-driven eviction as in the reference MemorySparseTable::shrink:
// ANY row whose decayed show falls under the threshold is evicted, trained
// or not — otherwise CTR tables grow without bound. Disk-resident rows are
// shrunk too (ssd_sparse_table.cc behavior): dropped entries leave the
// index, survivors get their decayed stats re-appended to the log.
int64_t pt_sparse_table_shrink(void* t, float decay, float threshold) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> maint(tab->maint_mu);
  int64_t dropped = 0;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto it = s.map.begin(); it != s.map.end();) {
      it->second.show *= decay;
      if (it->second.show < threshold) {
        it = s.map.erase(it);
        ++dropped;
      } else {
        ++it;
      }
    }
  }
  if (tab->ssd) {
    auto mem = mem_key_snapshot(tab);
    std::lock_guard<std::shared_mutex> g(tab->ssd->mu);
    std::vector<uint64_t> disk_keys;
    for (auto& kv : tab->ssd->index)
      if (!mem.count(kv.first)) disk_keys.push_back(kv.first);
    std::vector<std::pair<uint64_t, uint64_t>> republished;
    for (uint64_t key : disk_keys) {
      Row row;
      if (!tab->ssd_read_locked(key, row)) continue;
      row.show *= decay;
      if (row.show < threshold) {
        tab->ssd->index.erase(key);
        ++dropped;
      } else {
        uint64_t off;
        if (!tab->ssd_append_raw_locked(key, row, &off)) {
          // disk write failure: the old record (un-decayed show) still
          // backs the index; surface the error instead of silently making
          // cold disk rows un-evictable
          return -1;
        }
        republished.emplace_back(key, off);
      }
    }
    if (!republished.empty()) {
      // one flush for the whole batch, THEN publish (pread visibility)
      if (std::fflush(tab->ssd->f) != 0) return -1;
      for (auto& kv : republished) tab->ssd->index[kv.first] = kv.second;
    }
  }
  return dropped;
}

void pt_sparse_table_add_show(void* t, const uint64_t* keys, int64_t n,
                              float amount) {
  auto* tab = static_cast<Table*>(t);
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    // spilled rows fault back in: an impression on a disk-resident row must
    // count, or shrink wrongly evicts genuinely hot rows
    if (it == s.map.end()) it = tab->fault_in(s, keys[i]);
    if (it != s.map.end()) {
      it->second.show += amount;
      it->second.version = ++tab->global_version;  // mutation: see assign
    }
  }
}

// Binary save/load: header (magic, dim, count) then key + emb + state rows.
int pt_sparse_table_save(void* t, const char* path) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> maint(tab->maint_mu);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  const uint64_t magic = 0x50545350u;  // "PTSP"
  uint64_t count = 0;  // patched after the single write pass (no size()
                       // pre-pass: concurrent pushes would desync the header)
  uint64_t dim = static_cast<uint64_t>(tab->dim);
  std::fwrite(&magic, 8, 1, f);
  std::fwrite(&dim, 8, 1, f);
  long count_off = std::ftell(f);
  std::fwrite(&count, 8, 1, f);
  std::unordered_set<uint64_t> mem;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) {
      std::fwrite(&kv.first, 8, 1, f);
      std::fwrite(kv.second.emb.data(), sizeof(float), tab->dim, f);
      std::fwrite(kv.second.state.data(), sizeof(float), tab->dim, f);
      ++count;
      if (tab->ssd) mem.insert(kv.first);
    }
  }
  if (tab->ssd) {
    // disk-only rows belong in the checkpoint too (memory copy wins when
    // a key lives on both tiers)
    std::lock_guard<std::shared_mutex> g(tab->ssd->mu);
    std::vector<uint64_t> disk_keys;
    for (auto& kv : tab->ssd->index)
      if (!mem.count(kv.first)) disk_keys.push_back(kv.first);
    Row row;
    for (uint64_t key : disk_keys) {
      if (!tab->ssd_read_locked(key, row)) continue;
      std::fwrite(&key, 8, 1, f);
      std::fwrite(row.emb.data(), sizeof(float), tab->dim, f);
      std::fwrite(row.state.data(), sizeof(float), tab->dim, f);
      ++count;
    }
  }
  std::fseek(f, count_off, SEEK_SET);
  std::fwrite(&count, 8, 1, f);
  std::fclose(f);
  return 0;
}

int pt_sparse_table_load(void* t, const char* path) {
  auto* tab = static_cast<Table*>(t);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0, dim = 0, count = 0;
  if (std::fread(&magic, 8, 1, f) != 1 || magic != 0x50545350u ||
      std::fread(&dim, 8, 1, f) != 1 ||
      dim != static_cast<uint64_t>(tab->dim) ||
      std::fread(&count, 8, 1, f) != 1) {
    std::fclose(f);
    return -2;
  }
  std::vector<float> emb(tab->dim), state(tab->dim);
  for (uint64_t i = 0; i < count; ++i) {
    uint64_t key;
    if (std::fread(&key, 8, 1, f) != 1 ||
        std::fread(emb.data(), sizeof(float), tab->dim, f) !=
            static_cast<size_t>(tab->dim) ||
        std::fread(state.data(), sizeof(float), tab->dim, f) !=
            static_cast<size_t>(tab->dim)) {
      std::fclose(f);
      return -3;
    }
    Shard& s = tab->shard_of(key);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(key);
    // as in assign: fault in a spilled row so live show/click stats are
    // preserved regardless of which tier held the row pre-load (header
    // only — emb/state are overwritten from the checkpoint right below)
    if (it == s.map.end()) it = tab->fault_in(s, key, /*with_payload=*/false);
    if (it == s.map.end()) it = s.map.emplace(key, Row{}).first;
    Row& row = it->second;
    row.emb = emb;
    row.state = state;
    row.version = ++tab->global_version;  // mutation: see assign
    if (tab->ssd) {  // loaded row supersedes any stale disk record
      std::lock_guard<std::shared_mutex> g2(tab->ssd->mu);
      tab->ssd->index.erase(key);
    }
  }
  std::fclose(f);
  return 0;
}

// ---- SSD overflow tier (ssd_sparse_table.cc analog) ----

int pt_sparse_table_enable_ssd(void* t, const char* path) {
  auto* tab = static_cast<Table*>(t);
  auto tier = std::make_unique<DiskTier>();
  tier->path = path;
  tier->f = std::fopen(path, "w+b");
  if (!tier->f) return -1;
  tab->ssd = std::move(tier);
  return 0;
}

// Evict the coldest rows (oldest push version) beyond `max_mem_rows` to the
// disk log. Rows touched since the eviction snapshot stay resident. Returns
// rows evicted, or -2 on disk IO failure (rows whose append failed remain
// resident in memory — never erased on a failed write).
int64_t pt_sparse_table_spill(void* t, int64_t max_mem_rows) {
  auto* tab = static_cast<Table*>(t);
  if (!tab->ssd || max_mem_rows < 0) return -1;
  std::lock_guard<std::mutex> maint(tab->maint_mu);
  std::vector<std::pair<uint64_t, uint64_t>> vk;  // (version, key)
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) vk.emplace_back(kv.second.version, kv.first);
  }
  if (static_cast<int64_t>(vk.size()) <= max_mem_rows) return 0;
  int64_t need = static_cast<int64_t>(vk.size()) - max_mem_rows;
  std::nth_element(vk.begin(), vk.begin() + need, vk.end());
  // Pass A: append candidate rows to the log UNFLUSHED and UNPUBLISHED —
  // the rows stay memory-resident, so no reader consults the pending
  // records. One fflush then covers the whole batch (one syscall instead
  // of one per ~80-byte row). Pass B publishes each index entry and
  // erases the memory copy under the same shard lock, re-verifying the
  // version: a row pushed meanwhile stays resident and its orphaned
  // record is unindexed garbage that compact reclaims.
  struct Pending { uint64_t key, version, off; };
  std::vector<Pending> pend;
  pend.reserve(static_cast<size_t>(need));
  for (int64_t i = 0; i < need; ++i) {
    uint64_t snap_version = vk[i].first, key = vk[i].second;
    Shard& s = tab->shard_of(key);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(key);
    if (it == s.map.end() || it->second.version != snap_version) continue;
    uint64_t off;
    bool written;
    {
      std::lock_guard<std::shared_mutex> g2(tab->ssd->mu);
      written = tab->ssd_append_raw_locked(key, it->second, &off);
    }
    if (!written) return -2;  // disk full/IO error: keep the memory copy
    pend.push_back({key, snap_version, off});
  }
  {
    std::lock_guard<std::shared_mutex> g2(tab->ssd->mu);
    if (tab->ssd->f && std::fflush(tab->ssd->f) != 0) return -2;
  }
  int64_t evicted = 0;
  for (const Pending& p : pend) {
    Shard& s = tab->shard_of(p.key);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(p.key);
    if (it == s.map.end() || it->second.version != p.version) continue;
    {
      std::lock_guard<std::shared_mutex> g2(tab->ssd->mu);
      tab->ssd->index[p.key] = p.off;
    }
    s.map.erase(it);
    ++evicted;
  }
  return evicted;
}

// Rewrite the log keeping one live record per disk-only key (stale records
// from re-spills/faults/shrink are garbage). Returns live record count, or
// negative on IO error.
int64_t pt_sparse_table_ssd_compact(void* t) {
  auto* tab = static_cast<Table*>(t);
  if (!tab->ssd) return -1;
  // maint_mu: a concurrent spill between the mem snapshot and the index
  // rewrite would move a row to disk that compact then drops as
  // "memory-resident" — the row would vanish from both tiers
  std::lock_guard<std::mutex> maint(tab->maint_mu);
  auto mem = mem_key_snapshot(tab);
  std::lock_guard<std::shared_mutex> g(tab->ssd->mu);
  std::string tmp = tab->ssd->path + ".tmp";
  FILE* nf = std::fopen(tmp.c_str(), "w+b");
  if (!nf) return -2;
  std::unordered_map<uint64_t, uint64_t> new_index;
  Row row;
  for (auto& kv : tab->ssd->index) {
    if (mem.count(kv.first)) continue;  // memory copy is authoritative
    if (!tab->ssd_read_locked(kv.first, row)) continue;
    std::fseek(nf, 0, SEEK_END);
    uint64_t off = static_cast<uint64_t>(std::ftell(nf));
    size_t ok = 0;
    ok += std::fwrite(&kv.first, 8, 1, nf);
    ok += std::fwrite(&row.version, 8, 1, nf);
    ok += std::fwrite(&row.show, 4, 1, nf);
    ok += std::fwrite(&row.click, 4, 1, nf);
    ok += (std::fwrite(row.emb.data(), sizeof(float), tab->dim, nf) ==
           static_cast<size_t>(tab->dim));
    ok += (std::fwrite(row.state.data(), sizeof(float), tab->dim, nf) ==
           static_cast<size_t>(tab->dim));
    if (ok != 6) {
      // short write (disk full): keep the intact old log, discard the tmp
      std::fclose(nf);
      std::remove(tmp.c_str());
      return -4;
    }
    new_index[kv.first] = off;
  }
  // flush the rewritten log BEFORE publishing its index: pread readers
  // bypass the stdio buffer, so an unflushed record would read short and
  // a fault would mistake a live row for missing
  if (std::fflush(nf) != 0) {
    std::fclose(nf);
    std::remove(tmp.c_str());
    return -4;
  }
  std::fclose(tab->ssd->f);
  if (std::rename(tmp.c_str(), tab->ssd->path.c_str()) != 0) {
    // old log is gone from the handle but still on disk; reopen it and
    // discard the tmp file. A failed reopen leaves f null — the ssd_*
    // helpers treat that as "tier unavailable" rather than crashing.
    tab->ssd->f = std::fopen(tab->ssd->path.c_str(), "r+b");
    std::fclose(nf);
    std::remove(tmp.c_str());
    return -3;
  }
  tab->ssd->f = nf;
  tab->ssd->index = std::move(new_index);
  return static_cast<int64_t>(tab->ssd->index.size());
}

int64_t pt_sparse_table_ssd_rows(void* t) {
  auto* tab = static_cast<Table*>(t);
  if (!tab->ssd) return 0;
  auto mem = mem_key_snapshot(tab);
  std::shared_lock<std::shared_mutex> g(tab->ssd->mu);
  int64_t n = 0;
  for (auto& kv : tab->ssd->index)
    if (!mem.count(kv.first)) ++n;
  return n;
}

}  // extern "C"

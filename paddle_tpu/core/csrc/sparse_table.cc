// Memory sparse table — the host-resident embedding store of the PS
// subsystem. TPU-native counterpart of the reference's C++
// MemorySparseTable (paddle/fluid/distributed/ps/table/memory_sparse_table.cc)
// + SparseSgdRule accessors (ps/table/sparse_sgd_rule.cc): sharded hash maps
// with striped locks, lazily-initialized rows, and fused pull/push kernels so
// the hot path (CTR-scale embedding lookup/update) never touches Python.
//
// Exposed as a C ABI for ctypes binding (no pybind11 in this image).
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

struct Row {
  std::vector<float> emb;    // embedding weights [dim]
  std::vector<float> state;  // optimizer slot (adagrad G / momentum) [dim]
  uint64_t version = 0;      // bumped on every push (geo-sync watermark)
  float show = 0.f;          // CTR accessor statistics
  float click = 0.f;
};

struct Shard {
  std::unordered_map<uint64_t, Row> map;
  std::mutex mu;
};

enum class Optimizer : int { kSGD = 0, kAdagrad = 1, kMomentum = 2 };

struct Table {
  int dim;
  int shard_bits;
  Optimizer opt;
  float init_range;
  float lr_default;
  float momentum_or_eps;  // momentum coeff / adagrad epsilon
  std::vector<Shard> shards;
  std::atomic<uint64_t> global_version{0};
  uint64_t seed;

  Table(int d, int bits, int opt_kind, float init, float lr, float aux,
        uint64_t seed_)
      : dim(d),
        shard_bits(bits),
        opt(static_cast<Optimizer>(opt_kind)),
        init_range(init),
        lr_default(lr),
        momentum_or_eps(aux),
        shards(size_t(1) << bits),
        seed(seed_) {}

  inline Shard& shard_of(uint64_t key) {
    if (shard_bits == 0) return shards[0];
    // multiplicative hash → top bits pick the shard
    uint64_t h = key * 0x9E3779B97F4A7C15ull;
    return shards[h >> (64 - shard_bits)];
  }

  void init_row(Row& row, uint64_t key) {
    row.emb.resize(dim);
    row.state.assign(dim, 0.f);
    // deterministic in (key, table seed) only — identical across ranks and
    // restarts regardless of materialization order
    uint64_t h = (key ^ seed) * 0x9E3779B97F4A7C15ull;
    h ^= h >> 29;
    std::mt19937 gen(static_cast<uint32_t>(h ^ (h >> 32)));
    std::uniform_real_distribution<float> dist(-init_range, init_range);
    for (int i = 0; i < dim; ++i) row.emb[i] = dist(gen);
  }
};

}  // namespace

extern "C" {

void* pt_sparse_table_create(int dim, int shard_bits, int opt_kind,
                             float init_range, float lr, float aux,
                             uint64_t seed) {
  if (shard_bits < 0 || shard_bits > 16 || dim <= 0) return nullptr;
  return new Table(dim, shard_bits, opt_kind, init_range, lr, aux, seed);
}

void pt_sparse_table_destroy(void* t) { delete static_cast<Table*>(t); }

int pt_sparse_table_dim(void* t) { return static_cast<Table*>(t)->dim; }

uint64_t pt_sparse_table_size(void* t) {
  auto* tab = static_cast<Table*>(t);
  uint64_t n = 0;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    n += s.map.size();
  }
  return n;
}

// Pull rows for n keys into out[n * dim]; missing keys are initialized
// (create_if_missing != 0) or zero-filled.
void pt_sparse_table_pull(void* t, const uint64_t* keys, int64_t n,
                          float* out, int create_if_missing) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it == s.map.end()) {
      if (!create_if_missing) {
        std::memset(out + i * dim, 0, sizeof(float) * dim);
        continue;
      }
      it = s.map.emplace(keys[i], Row{}).first;
      tab->init_row(it->second, keys[i]);
    }
    std::memcpy(out + i * dim, it->second.emb.data(), sizeof(float) * dim);
  }
}

// Apply gradients for n keys (duplicate keys fold sequentially — downpour
// semantics). lr<=0 uses the table default.
void pt_sparse_table_push(void* t, const uint64_t* keys, int64_t n,
                          const float* grads, float lr) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  const float eta = lr > 0.f ? lr : tab->lr_default;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it == s.map.end()) {
      it = s.map.emplace(keys[i], Row{}).first;
      tab->init_row(it->second, keys[i]);
    }
    Row& row = it->second;
    const float* gi = grads + i * dim;
    switch (tab->opt) {
      case Optimizer::kSGD:
        for (int d = 0; d < dim; ++d) row.emb[d] -= eta * gi[d];
        break;
      case Optimizer::kAdagrad:
        for (int d = 0; d < dim; ++d) {
          row.state[d] += gi[d] * gi[d];
          row.emb[d] -=
              eta * gi[d] / (std::sqrt(row.state[d]) + tab->momentum_or_eps);
        }
        break;
      case Optimizer::kMomentum:
        for (int d = 0; d < dim; ++d) {
          row.state[d] = tab->momentum_or_eps * row.state[d] + gi[d];
          row.emb[d] -= eta * row.state[d];
        }
        break;
    }
    row.version = ++tab->global_version;
  }
}

// Overwrite rows (used by load / broadcast init).
void pt_sparse_table_assign(void* t, const uint64_t* keys, int64_t n,
                            const float* vals) {
  auto* tab = static_cast<Table*>(t);
  const int dim = tab->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    Row& row = s.map[keys[i]];
    if (row.emb.empty()) {
      row.emb.resize(dim);
      row.state.assign(dim, 0.f);
    }
    std::memcpy(row.emb.data(), vals + i * dim, sizeof(float) * dim);
  }
}

// Snapshot keys into out_keys[size()] (caller allocates via size()).
int64_t pt_sparse_table_keys(void* t, uint64_t* out_keys, int64_t cap) {
  auto* tab = static_cast<Table*>(t);
  int64_t n = 0;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) {
      if (n >= cap) return n;
      out_keys[n++] = kv.first;
    }
  }
  return n;
}

// Drop rows whose show-count decays below `threshold` (table shrink).
// Accessor-driven eviction as in the reference MemorySparseTable::shrink:
// ANY row whose decayed show falls under the threshold is evicted, trained
// or not — otherwise CTR tables grow without bound.
int64_t pt_sparse_table_shrink(void* t, float decay, float threshold) {
  auto* tab = static_cast<Table*>(t);
  int64_t dropped = 0;
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto it = s.map.begin(); it != s.map.end();) {
      it->second.show *= decay;
      if (it->second.show < threshold) {
        it = s.map.erase(it);
        ++dropped;
      } else {
        ++it;
      }
    }
  }
  return dropped;
}

void pt_sparse_table_add_show(void* t, const uint64_t* keys, int64_t n,
                              float amount) {
  auto* tab = static_cast<Table*>(t);
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = tab->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(keys[i]);
    if (it != s.map.end()) it->second.show += amount;
  }
}

// Binary save/load: header (magic, dim, count) then key + emb + state rows.
int pt_sparse_table_save(void* t, const char* path) {
  auto* tab = static_cast<Table*>(t);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  const uint64_t magic = 0x50545350u;  // "PTSP"
  uint64_t count = 0;  // patched after the single write pass (no size()
                       // pre-pass: concurrent pushes would desync the header)
  uint64_t dim = static_cast<uint64_t>(tab->dim);
  std::fwrite(&magic, 8, 1, f);
  std::fwrite(&dim, 8, 1, f);
  long count_off = std::ftell(f);
  std::fwrite(&count, 8, 1, f);
  for (auto& s : tab->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.map) {
      std::fwrite(&kv.first, 8, 1, f);
      std::fwrite(kv.second.emb.data(), sizeof(float), tab->dim, f);
      std::fwrite(kv.second.state.data(), sizeof(float), tab->dim, f);
      ++count;
    }
  }
  std::fseek(f, count_off, SEEK_SET);
  std::fwrite(&count, 8, 1, f);
  std::fclose(f);
  return 0;
}

int pt_sparse_table_load(void* t, const char* path) {
  auto* tab = static_cast<Table*>(t);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0, dim = 0, count = 0;
  if (std::fread(&magic, 8, 1, f) != 1 || magic != 0x50545350u ||
      std::fread(&dim, 8, 1, f) != 1 ||
      dim != static_cast<uint64_t>(tab->dim) ||
      std::fread(&count, 8, 1, f) != 1) {
    std::fclose(f);
    return -2;
  }
  std::vector<float> emb(tab->dim), state(tab->dim);
  for (uint64_t i = 0; i < count; ++i) {
    uint64_t key;
    if (std::fread(&key, 8, 1, f) != 1 ||
        std::fread(emb.data(), sizeof(float), tab->dim, f) !=
            static_cast<size_t>(tab->dim) ||
        std::fread(state.data(), sizeof(float), tab->dim, f) !=
            static_cast<size_t>(tab->dim)) {
      std::fclose(f);
      return -3;
    }
    Shard& s = tab->shard_of(key);
    std::lock_guard<std::mutex> g(s.mu);
    Row& row = s.map[key];
    row.emb = emb;
    row.state = state;
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"

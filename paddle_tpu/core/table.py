"""Python wrappers over the native core (ctypes)."""
from __future__ import annotations

import ctypes
import pickle

import numpy as np

from . import load_library

__all__ = ["SparseTable", "BlockingQueue"]

_OPT = {"sgd": 0, "adagrad": 1, "momentum": 2}


class SparseTable:
    """Host-resident sparse embedding table (C++ MemorySparseTable analog).

    pull/push move (keys, float rows) across the ctypes boundary with
    zero-copy numpy views; all hashing/updating happens in native code.
    """

    def __init__(self, dim, shard_bits=6, optimizer="adagrad",
                 init_range=0.01, lr=0.05, aux=1e-6, seed=0,
                 ssd_path=None, mem_budget_rows=0):
        self._lib = load_library()
        self._h = self._lib.pt_sparse_table_create(
            int(dim), int(shard_bits), _OPT[optimizer], float(init_range),
            float(lr), float(aux), int(seed))
        if not self._h:
            raise ValueError("bad sparse table config")
        self.dim = int(dim)
        self.optimizer = optimizer
        # SSD overflow tier (reference ssd_sparse_table.cc): cold rows spill
        # to a log file past mem_budget_rows; pull/push fault them back in
        self.mem_budget_rows = int(mem_budget_rows)
        self._push_count = 0
        if ssd_path is not None:
            self.enable_ssd(ssd_path)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pt_sparse_table_destroy(h)
            self._h = None

    @staticmethod
    def _keys_arr(keys):
        arr = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                   dtype=np.uint64)
        return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))

    def pull(self, keys, create_if_missing=True):
        arr, kp = self._keys_arr(keys)
        out = np.empty((arr.size, self.dim), dtype=np.float32)
        self._lib.pt_sparse_table_pull(
            self._h, kp, arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            1 if create_if_missing else 0)
        self._maybe_auto_spill()  # fault-ins/creates count against budget
        return out

    def push(self, keys, grads, lr=-1.0):
        arr, kp = self._keys_arr(keys)
        g = np.ascontiguousarray(np.asarray(grads, dtype=np.float32)
                                 .reshape(arr.size, self.dim))
        self._lib.pt_sparse_table_push(
            self._h, kp, arr.size,
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), float(lr))
        self._maybe_auto_spill()

    def _maybe_auto_spill(self):
        """Enforce mem_budget_rows: check residency every ~64 pull/push
        calls (the check walks the shards) and evict past 1.25x budget
        down to budget. Pull-driven fault-in and row creation grow memory
        exactly like pushes do, so both paths count."""
        if not self.mem_budget_rows:
            return
        self._push_count += 1
        if self._push_count % 64 == 0 and (
                self.mem_rows() > self.mem_budget_rows * 1.25):
            self.spill(self.mem_budget_rows)

    def assign(self, keys, values):
        arr, kp = self._keys_arr(keys)
        v = np.ascontiguousarray(np.asarray(values, dtype=np.float32)
                                 .reshape(arr.size, self.dim))
        self._lib.pt_sparse_table_assign(
            self._h, kp, arr.size,
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))

    def add(self, keys, deltas):
        """Atomic server-side += (geo-SGD delta merge)."""
        arr, kp = self._keys_arr(keys)
        v = np.ascontiguousarray(np.asarray(deltas, dtype=np.float32)
                                 .reshape(arr.size, self.dim))
        self._lib.pt_sparse_table_add(
            self._h, kp, arr.size,
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))

    def keys(self):
        n = len(self)
        out = np.empty(n, dtype=np.uint64)
        got = self._lib.pt_sparse_table_keys(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n)
        return out[:got]

    def shrink(self, decay=0.98, threshold=1.0):
        n = int(self._lib.pt_sparse_table_shrink(self._h, float(decay),
                                                 float(threshold)))
        if n < 0:
            raise IOError("shrink hit a disk write failure on the SSD tier")
        return n

    def add_show(self, keys, amount=1.0):
        arr, kp = self._keys_arr(keys)
        self._lib.pt_sparse_table_add_show(self._h, kp, arr.size,
                                           float(amount))

    def save(self, path):
        rc = self._lib.pt_sparse_table_save(self._h, path.encode())
        if rc != 0:
            raise IOError(f"sparse table save failed rc={rc}")

    def load(self, path):
        rc = self._lib.pt_sparse_table_load(self._h, path.encode())
        if rc != 0:
            raise IOError(f"sparse table load failed rc={rc}")

    def __len__(self):
        return int(self._lib.pt_sparse_table_size(self._h))

    # ---- SSD overflow tier ----

    def enable_ssd(self, path):
        rc = self._lib.pt_sparse_table_enable_ssd(self._h, str(path).encode())
        if rc != 0:
            raise IOError(f"enable_ssd({path}) failed rc={rc}")

    def spill(self, max_mem_rows=None):
        """Evict the coldest rows beyond the budget to the disk log."""
        budget = self.mem_budget_rows if max_mem_rows is None else max_mem_rows
        n = int(self._lib.pt_sparse_table_spill(self._h, int(budget)))
        if n == -1:
            raise RuntimeError("spill needs enable_ssd()/ssd_path first")
        if n < 0:
            raise IOError("spill hit a disk write failure; unwritten rows "
                          "remain in memory")
        return n

    def ssd_compact(self):
        """Rewrite the log dropping stale records; returns live row count."""
        n = int(self._lib.pt_sparse_table_ssd_compact(self._h))
        if n < 0:
            raise RuntimeError(f"ssd_compact failed rc={n}")
        return n

    def mem_rows(self):
        return int(self._lib.pt_sparse_table_mem_rows(self._h))

    def ssd_rows(self):
        return int(self._lib.pt_sparse_table_ssd_rows(self._h))


class BlockingQueue:
    """Bounded native queue of pickled python objects
    (LoDTensorBlockingQueue analog for DataLoader prefetch)."""

    def __init__(self, capacity=64):
        self._lib = load_library()
        self._h = self._lib.pt_queue_create(int(capacity))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pt_queue_destroy(h)
            self._h = None

    def push(self, obj, timeout_ms=-1):
        buf = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        arr = np.frombuffer(buf, dtype=np.uint8)
        rc = self._lib.pt_queue_push(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            arr.size, int(timeout_ms))
        if rc == -1:
            raise RuntimeError("queue closed")
        if rc == -2:
            raise TimeoutError("queue push timeout")

    def pop(self, timeout_ms=-1):
        """Returns the object, or None when the queue is closed & drained."""
        while True:
            n = self._lib.pt_queue_pop_size(self._h, int(timeout_ms))
            if n == 0:
                return None
            if n == -2:
                raise TimeoutError("queue pop timeout")
            out = np.empty(n, dtype=np.uint8)
            got = self._lib.pt_queue_pop(
                self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                n)
            if got > 0:
                return pickle.loads(out[:got].tobytes())
            # lost the race to another consumer between size-peek and pop
            # (got == 0: queue emptied; got == -3: different item at front) —
            # re-peek; a closed+drained queue still returns None via n == 0

    def close(self):
        self._lib.pt_queue_close(self._h)

    def __len__(self):
        return int(self._lib.pt_queue_size(self._h))

    @property
    def closed(self):
        return bool(self._lib.pt_queue_is_closed(self._h))

"""paddle.static.nn — layer-builder functions for static graphs.

Parity with python/paddle/static/nn/ (fc, conv2d, batch_norm, embedding, …):
each call builds the matching paddle_tpu.nn layer (parameters are created and
registered on the active Program so they survive as tape externals) and
applies it, so the ops land on the Program tape.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fc", "embedding", "conv2d", "batch_norm", "layer_norm", "dropout"]


def _keep(layer):
    from . import _current_program

    _current_program()._layers.append(layer)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully connected: dims [num_flatten_dims:] contract against the weight,
    dims [:num_flatten_dims] stay (reference static.nn.fc semantics)."""
    from .. import nn

    nfd = num_flatten_dims
    shape = [int(d) for d in x.shape]
    in_f = 1
    for d in shape[nfd:]:
        in_f *= d
    if shape[nfd:] != [in_f]:
        # collapse the contracted dims; keep dims [:nfd] (batch dim dynamic)
        x = x.reshape([-1] + shape[1:nfd] + [in_f])
    layer = _keep(nn.Linear(in_f, size, weight_attr=weight_attr,
                            bias_attr=bias_attr, name=name))
    out = layer(x)  # Linear contracts the last dim, keeping leading dims
    if activation:
        import paddle_tpu.nn.functional as F

        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              weight_attr=None, name=None):
    from .. import nn

    layer = _keep(nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                               weight_attr=weight_attr, name=name))
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from .. import nn

    in_c = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _keep(nn.Conv2D(in_c, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from .. import nn

    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = _keep(nn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                                 weight_attr=param_attr, bias_attr=bias_attr,
                                 data_format=data_layout))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn

    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    layer = _keep(nn.LayerNorm(shape, epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    import paddle_tpu.nn.functional as F

    return F.dropout(x, p=dropout_prob, training=not is_test)


# sequence op family (reference: paddle.static.nn.sequence_* over
# fluid/operators/sequence_ops/; padded+lengths carrier — see
# nn/functional/sequence.py)
from ..nn.functional.sequence import (  # noqa: F401,E402
    sequence_concat, sequence_expand, sequence_first_step, sequence_last_step,
    sequence_mask, sequence_pad, sequence_pool, sequence_reverse,
    sequence_slice, sequence_softmax, sequence_unpad,
)


# --------------------------------------------------------------------------
# control-flow ops (reference: operators/controlflow/ while_op.cc,
# conditional_block_op.cc; python API paddle.static.nn.cond/while_loop/
# case/switch_case in python/paddle/fluid/layers/control_flow.py)
# --------------------------------------------------------------------------

def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Run ``body`` while ``cond(*loop_vars)`` holds, as ONE structured op.

    TPU-native: records a single tape op whose kernel is
    ``jax.lax.while_loop`` — the XLA analog of the reference's while_op
    block (operators/controlflow/while_op.cc). The trip count stays
    data-dependent at runtime (it is NOT baked at Program-build time).

    Like ``jax.lax.while_loop``, the op has no reverse-mode gradient; the
    loop runs under no_grad and its outputs carry stop_gradient=True (the
    reference's while grad op has no XLA equivalent).

    ``cond``/``body`` may reference other tensors from the enclosing scope;
    DIRECT references (closure cells, module globals, functools.partial
    args, a bound method's self/closure) are captured as implicit op inputs
    so Program replay sees live feed values. A tensor reached only through a
    helper function the branch calls is NOT discoverable — pass it via
    ``loop_vars`` instead.
    """
    import functools

    import jax

    from ..framework.autograd import call_op, no_grad
    from ..framework.tensor import Tensor

    flat = list(loop_vars)
    if not flat:
        raise ValueError("loop_vars must be non-empty")
    for v in flat:
        if not isinstance(v, Tensor):
            raise TypeError("while_loop loop_vars must be Tensors "
                            f"(got {type(v).__name__})")
    protos = flat

    # Tensors captured in cond/body closure cells (e.g. a fed `n` bound in
    # `lambda i, a: i < n`) become implicit op inputs, so Program replay
    # substitutes the live feed value instead of the build-time placeholder
    # (the reference wires these as while-block inputs the same way).
    captured = []
    seen = {id(p) for p in protos}

    def _capture(c):
        items = c if isinstance(c, (list, tuple)) else [c]
        for it in items:
            if isinstance(it, Tensor) and id(it) not in seen:
                seen.add(id(it))
                captured.append(it)

    def _scan_fn(f, depth=0):
        if depth > 2:
            return
        if isinstance(f, functools.partial):
            _capture(list(f.args) + list(f.keywords.values()))
            _scan_fn(f.func, depth + 1)
            return
        if hasattr(f, "__func__"):  # bound method: scan self attrs too
            self_obj = getattr(f, "__self__", None)
            if self_obj is not None:
                _capture([v for v in getattr(self_obj, "__dict__",
                                             {}).values()
                          if isinstance(v, Tensor)])
            _scan_fn(f.__func__, depth + 1)
            return
        for cell in (getattr(f, "__closure__", None) or ()):
            try:
                _capture(cell.cell_contents)
            except ValueError:
                continue
        # module-level scripts bind outer tensors as globals, not cells
        code = getattr(f, "__code__", None)
        if code is not None:
            for nm in code.co_names:
                if nm in getattr(f, "__globals__", {}):
                    _capture(f.__globals__[nm])

    for f in (cond, body):
        _scan_fn(f)
    n_loop = len(flat)

    def _wrap(vals):
        out = []
        for v, p in zip(vals, protos):
            t = Tensor(v, _internal=True)
            t.stop_gradient = True
            out.append(t)
        return tuple(out)

    def _unwrap(out):
        seq = out if isinstance(out, (list, tuple)) else [out]
        if len(seq) != len(protos):
            raise ValueError(
                f"body returned {len(seq)} values; expected {len(protos)}")
        return tuple(jnp.asarray(o._value if isinstance(o, Tensor) else o)
                     for o in seq)

    def fn(*vals):
        from ..framework import autograd as _ag

        loop_vals, clos_vals = vals[:n_loop], vals[n_loop:]

        def _paused(thunk):
            # inner ops run on while tracers: they must not land on the
            # Program tape (only the outer while op is the recorded node)
            prev = _ag.set_op_recorder(None)
            old = [t._value for t in captured]
            for t, v in zip(captured, clos_vals):
                t._value = v
            try:
                with no_grad():
                    return thunk()
            finally:
                for t, v in zip(captured, old):
                    t._value = v
                _ag.set_op_recorder(prev)

        def c(vs):
            r = _paused(lambda: cond(*_wrap(vs)))
            r = r._value if isinstance(r, Tensor) else r
            return jnp.asarray(r).astype(bool).reshape(())

        def b(vs):
            return _paused(lambda: _unwrap(body(*_wrap(vs))))

        return jax.lax.while_loop(
            c, b, tuple(jnp.asarray(v) for v in loop_vals))

    with no_grad():  # lax.while_loop has no reverse-mode derivative
        out = call_op(fn, *flat, *captured, op_name="while_loop")
    out = out if isinstance(out, (list, tuple)) else [out]
    for t in out:
        t.stop_gradient = True
    return list(out)


def _select_outputs(pred, a_out, b_out, op_label):
    """Elementwise select between two same-structure branch outputs."""
    from ..framework.autograd import call_op
    from ..framework.tensor import Tensor

    seq_a = a_out if isinstance(a_out, (list, tuple)) else [a_out]
    seq_b = b_out if isinstance(b_out, (list, tuple)) else [b_out]
    if len(seq_a) != len(seq_b):
        raise ValueError(
            f"{op_label}: branches returned {len(seq_a)} vs {len(seq_b)} "
            "outputs; structures must match")
    outs = []
    for a, b in zip(seq_a, seq_b):
        if not isinstance(a, Tensor) or not isinstance(b, Tensor):
            raise TypeError(f"{op_label}: branch outputs must be Tensors")

        def fn(p, av, bv):
            return jnp.where(jnp.asarray(p).astype(bool).reshape(()), av, bv)

        outs.append(call_op(fn, pred, a, b, op_name=op_label))
    if not isinstance(a_out, (list, tuple)):
        return outs[0]
    return type(a_out)(outs) if isinstance(a_out, tuple) else outs


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Two-way branch on a boolean tensor (reference:
    conditional_block_op.cc; API control_flow.py cond).

    TPU-native semantics: BOTH branches execute and a select picks the
    result per element of the predicate's truth value — XLA's select
    idiom, correct (and differentiable) for the side-effect-free branch
    functions the static API requires. Branch outputs must match in
    structure, shape and dtype (the reference shares this constraint).
    """
    from ..framework.tensor import Tensor

    if true_fn is None or false_fn is None:
        raise ValueError("cond requires both true_fn and false_fn")
    if not isinstance(pred, Tensor):
        import numpy as _np

        return true_fn() if bool(_np.asarray(pred)) else false_fn()
    return _select_outputs(pred, true_fn(), false_fn(), "cond")


def case(pred_fn_pairs, default=None, name=None):
    """First-match multi-way branch (reference: control_flow.py case)."""
    pred_fn_pairs = list(pred_fn_pairs)
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    if default is None:
        # reference semantics: the last pair's fn doubles as the default
        default = pred_fn_pairs[-1][1]
    out = default()
    # evaluate in reverse: earlier predicates take precedence
    for pred, fn in reversed(list(pred_fn_pairs)):
        out = _select_outputs(pred, fn(), out, "case")
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer-indexed branch (reference: control_flow.py switch_case)."""
    from ..framework.autograd import call_op

    if isinstance(branch_fns, dict):
        items = list(branch_fns.items())
    else:
        seq = list(branch_fns)
        # both forms the reference accepts: [fn, ...] and [(index, fn), ...]
        if seq and isinstance(seq[0], (tuple, list)):
            items = [(int(i), f) for i, f in seq]
        else:
            items = list(enumerate(seq))
    if default is None:
        default = items[-1][1]
    out = default()
    for idx, fn in reversed(items):
        def eq(bi, _i=int(idx)):
            return (jnp.asarray(bi).reshape(()) == _i)

        pred = call_op(eq, branch_index, op_name="switch_case_eq")
        out = _select_outputs(pred, fn(), out, "switch_case")
    return out


__all__ += ["while_loop", "cond", "case", "switch_case"]


# --------------------------------------------------------------------------
# layer-builder tail (reference: python/paddle/static/nn/__init__.py)
# --------------------------------------------------------------------------

def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    from .. import nn

    c_in = int(input.shape[1])
    layer = _keep(nn.Conv2DTranspose(
        c_in, num_filters, filter_size, stride=stride, padding=padding,
        output_padding=output_padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr))
    out = layer(input)
    return _maybe_act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    from .. import nn

    c_in = int(input.shape[1])
    layer = _keep(nn.Conv3D(c_in, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr))
    return _maybe_act(layer(input), act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, name=None):
    from .. import nn

    c_in = int(input.shape[1])
    layer = _keep(nn.Conv3DTranspose(
        c_in, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _maybe_act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn

    layer = _keep(nn.GroupNorm(groups, int(input.shape[1]), epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr))
    return _maybe_act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn

    c = int(input.shape[1])
    cls = {3: nn.InstanceNorm1D, 4: nn.InstanceNorm2D}.get(
        len(input.shape), nn.InstanceNorm3D)
    layer = _keep(cls(c, epsilon=epsilon, weight_attr=param_attr,
                      bias_attr=bias_attr))
    return layer(input)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn

    num = 1
    if mode == "channel":
        num = int(x.shape[1] if data_format == "NCHW" else x.shape[-1])
    elif mode == "element":
        num = 1
        for d in x.shape[1:]:
            num *= int(d)
    layer = _keep(nn.PReLU(num_parameters=num, weight_attr=param_attr,
                           data_format=data_format))
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized VALUE of a weight tensor (reference:
    spectral_norm_op.cc): w / sigma_max, sigma estimated by power
    iteration. The layer-parameter variant lives in nn.utils."""
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    d = int(dim)

    def fn(w):
        mat = jnp.moveaxis(w, d, 0).reshape(w.shape[d], -1)
        u = jnp.ones((mat.shape[0],), w.dtype) / jnp.sqrt(mat.shape[0])
        for _ in range(max(int(power_iters), 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        return w / jnp.maximum(sigma, eps)

    return call_op(fn, weight, op_name="spectral_norm")


def data_norm(input, epsilon=1e-5, param_attr=None, name=None,
              slot_dim=-1, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Global data normalization from accumulated batch statistics
    (reference: data_norm_op.cc, CTR models): persistable
    batch_size/batch_sum/batch_square_sum accumulators (initialized to the
    reference's 1e4/0/1e4 so the first batches are near-identity) imply
    mean = sum/n and scale = sqrt(n/square_sum); each call folds the
    current batch into the accumulators with `summary_decay_rate`."""
    from ..framework.autograd import call_op
    from ..framework.tensor import create_parameter
    from ..nn.initializer import Constant

    c = int(input.shape[-1])
    batch_size = create_parameter([c], "float32", attr=param_attr,
                                  default_initializer=Constant(1e4))
    batch_sum = create_parameter([c], "float32", attr=param_attr,
                                 default_initializer=Constant(0.0))
    batch_sq = create_parameter([c], "float32", attr=param_attr,
                                default_initializer=Constant(1e4))
    for p in (batch_size, batch_sum, batch_sq):
        p.stop_gradient = True  # accumulators, not grad-trained

    def fn(v, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(sq, epsilon))
        return (v - mean) * scale

    out = call_op(fn, input, batch_size, batch_sum, batch_sq,
                  op_name="data_norm")
    # fold this batch into the accumulators (the reference op's side output)
    import numpy as np

    v = np.asarray(input.numpy(), np.float32).reshape(-1, c)
    d = float(summary_decay_rate)
    batch_size._value = batch_size._value * d + v.shape[0]
    batch_sum._value = batch_sum._value * d + v.sum(0)
    batch_sq._value = batch_sq._value * d + (v * v).sum(0)
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import nn

    layer = _keep(nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), int(size),
                              weight_attr=param_attr, bias_attr=bias_attr))
    return _maybe_act(layer(x, y), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead (row) convolution (reference: row_conv_op.cc): each step
    mixes the next `future_context_size` steps: out[t] = sum_k w[k]*x[t+k]."""
    import jax.numpy as jnp

    from ..framework.tensor import create_parameter
    from ..framework.autograd import call_op

    k = int(future_context_size) + 1
    d = int(input.shape[-1])
    w = create_parameter([k, d], "float32", attr=param_attr)

    def fn(v, wv):
        pad = [(0, 0)] * v.ndim
        pad[-2] = (0, k - 1)
        vp = jnp.pad(v, pad)
        out = 0.0
        T = v.shape[-2]
        for i in range(k):
            out = out + jnp.take(vp, jnp.arange(i, i + T), axis=-2) * wv[i]
        return out

    return _maybe_act(call_op(fn, input, w, op_name="row_conv"), act)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    """PS-backed large-scale embedding (reference: contrib
    sparse_embedding → distributed_lookup_table). Same call surface as
    embedding with is_sparse=True: backward produces row-sparse grads."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     weight_attr=param_attr)


def crf_decoding(input, param_attr=None, length=None, label=None,
                 transition=None, name=None):
    """Viterbi decode of linear-chain CRF emissions (reference:
    crf_decoding_op.cc). `transition` may be given directly (the
    linear_chain_crf transition parameter); otherwise one is created."""
    from ..framework.tensor import create_parameter
    from ..text import viterbi_decode

    n_tags = int(input.shape[-1])
    if transition is None:
        transition = create_parameter([n_tags + 2, n_tags], "float32",
                                      attr=param_attr)
    # strip the start/stop rows the reference keeps in the parameter
    trans = transition[2:] if int(transition.shape[0]) == n_tags + 2 \
        else transition
    _scores, path = viterbi_decode(input, trans, lengths=length,
                                   include_bos_eos_tag=False)
    return path


def sequence_conv(input, num_filters, filter_size=3, padding=True,
                  param_attr=None, bias_attr=None, act=None):
    """1-D convolution over the time axis of (padded [B,T,D]) sequences
    (reference: sequence_conv_op.cc)."""
    from .. import nn

    layer = _keep(nn.Conv1D(int(input.shape[-1]), num_filters, filter_size,
                            padding=(int(filter_size) // 2 if padding else 0),
                            data_format="NLC", weight_attr=param_attr,
                            bias_attr=bias_attr))
    return _maybe_act(layer(input), act)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding-window id enumeration (reference:
    sequence_enumerate_op.cc): out[i] = [x[i], x[i+1], ..x[i+w-1]] with
    tail padding."""
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    w = int(win_size)

    def fn(v):
        T = v.shape[-1]
        vp = jnp.concatenate(
            [v, jnp.full(v.shape[:-1] + (w - 1,), pad_value, v.dtype)], -1)
        cols = [jnp.take(vp, jnp.arange(i, i + T), axis=-1)
                for i in range(w)]
        return jnp.stack(cols, axis=-1)

    return call_op(fn, input, op_name="sequence_enumerate")


def sequence_expand_as(x, y, name=None):
    """Tile each row of x to match y's row count per sequence — with the
    padded carrier both sides share [B, T, ...]: broadcast x's rows
    (reference: sequence_expand_as_op.cc)."""
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    def fn(xv, yv):
        reps = yv.shape[1] if yv.ndim > 1 else 1
        if xv.ndim == 2 and yv.ndim >= 2 and xv.shape[1] != yv.shape[1]:
            return jnp.repeat(xv, yv.shape[1] // xv.shape[1], axis=1)
        return jnp.broadcast_to(xv, yv.shape[:2] + xv.shape[2:])

    return call_op(fn, x, y, op_name="sequence_expand_as")


def sequence_reshape(input, new_dim, name=None):
    """Re-chunk the feature dim of flat sequence rows (reference:
    sequence_reshape_op.cc): [N, D] -> [N*D/new_dim, new_dim]."""
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    nd = int(new_dim)
    return call_op(lambda v: v.reshape(-1, nd), input,
                   op_name="sequence_reshape")


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates into sequence positions (reference:
    sequence_scatter_op.cc): out[b, index[b, i]] += updates[b, i]."""
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    def fn(v, idx, upd):
        b = jnp.arange(v.shape[0])[:, None]
        return v.at[b, idx.astype(jnp.int32)].add(upd)

    return call_op(fn, input, index, updates, op_name="sequence_scatter")


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference: nce_op.cc): logistic
    discrimination of the true class against `num_neg_samples` sampled
    noise classes, avoiding the full-vocab softmax."""
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import create_parameter
    from ..framework.autograd import call_op
    from ..framework import random as rng_mod

    d = int(input.shape[-1])
    n_cls = int(num_total_classes)
    w = create_parameter([n_cls, d], "float32", attr=param_attr)
    b = create_parameter([n_cls], "float32", attr=bias_attr, is_bias=True)
    k = int(num_neg_samples)
    key = rng_mod.next_key()

    def fn(v, lbl, wv, bv):
        neg = jax.random.randint(key, (v.shape[0], k), 0, n_cls)
        lbl2 = lbl.reshape(-1, 1).astype(jnp.int32)
        pos_logit = jnp.sum(v * wv[lbl2[:, 0]], -1) + bv[lbl2[:, 0]]
        neg_logit = jnp.einsum("bd,bkd->bk", v, wv[neg]) + bv[neg]
        # uniform-sampler noise odds k*q(w) = k/n_cls (reference nce_op.h:
        # b = num_neg_samples / num_total_classes)
        log_kq = jnp.log(jnp.asarray(float(k) / float(n_cls)))
        pos = jax.nn.log_sigmoid(pos_logit - log_kq)
        negl = jax.nn.log_sigmoid(-(neg_logit - log_kq)).sum(-1)
        return -(pos + negl).reshape(-1, 1)

    return call_op(fn, input, label, w, b, op_name="nce")


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, name=None):
    """SSD multi-box head (reference: fluid/layers/detection.py
    multi_box_head): per-feature-map 3x3 conv loc/conf predictors +
    prior boxes, concatenated across maps. Returns
    (mbox_loc, mbox_conf, boxes, variances)."""
    import numpy as np

    from .. import nn
    from ..tensor import concat
    from ..vision.detection import prior_box as _prior_box

    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max_ratio
        n = len(inputs)
        step = int((max_ratio - min_ratio) / (n - 2)) if n > 2 else 0
        min_sizes = [base_size * 0.1] + [
            base_size * (min_ratio + i * step) / 100.0 for i in range(n - 1)]
        max_sizes = [base_size * 0.2] + [
            base_size * (min_ratio + (i + 1) * step) / 100.0
            for i in range(n - 1)]
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        mn = min_sizes[i] if isinstance(min_sizes, (list, tuple)) else min_sizes
        mx = (max_sizes[i] if isinstance(max_sizes, (list, tuple))
              else max_sizes) if max_sizes else None
        ar = aspect_ratios[i] if isinstance(
            aspect_ratios[0], (list, tuple)) else aspect_ratios
        boxes, variances = _prior_box(
            feat, image, [mn] if np.isscalar(mn) else mn,
            [mx] if (mx is not None and np.isscalar(mx)) else mx,
            ar, flip=flip, clip=clip,
            steps=[steps[i], steps[i]] if steps else (0.0, 0.0),
            offset=offset)
        n_priors = int(np.prod(boxes.shape[:-1]) // (
            int(feat.shape[2]) * int(feat.shape[3])))
        c_in = int(feat.shape[1])
        loc_conv = _keep(nn.Conv2D(c_in, n_priors * 4, 3, padding=1))
        conf_conv = _keep(nn.Conv2D(c_in, n_priors * num_classes, 3,
                                    padding=1))
        loc = loc_conv(feat).transpose([0, 2, 3, 1]).reshape([
            int(feat.shape[0]), -1, 4])
        conf = conf_conv(feat).transpose([0, 2, 3, 1]).reshape([
            int(feat.shape[0]), -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(boxes.reshape([-1, 4]))
        vars_all.append(variances.reshape([-1, 4]))
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes_all, axis=0), concat(vars_all, axis=0))


def _maybe_act(out, act):
    if act:
        import paddle_tpu.nn.functional as F

        return getattr(F, act)(out)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: paddle.static.py_func — re-exported from the static
    package (defined there; late import avoids the circular init)."""
    from . import py_func as _py_func

    return _py_func(func, x, out, backward_func=backward_func,
                    skip_vars_in_backward_input=skip_vars_in_backward_input)


__all__ += [
    "conv2d_transpose", "conv3d", "conv3d_transpose", "group_norm",
    "instance_norm", "prelu", "spectral_norm", "data_norm",
    "bilinear_tensor_product", "row_conv", "sparse_embedding",
    "crf_decoding", "sequence_conv", "sequence_enumerate",
    "sequence_expand_as", "sequence_reshape", "sequence_scatter", "nce",
    "multi_box_head", "py_func",
]


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """Deformable conv v2 builder (reference: static.nn.deform_conv2d over
    deformable_conv_op.cu). The sampling kernel is the shared
    vision.ops.deform_conv2d implementation."""
    from ..framework.tensor import create_parameter
    from ..vision.ops import deform_conv2d as _dc

    ks = filter_size if isinstance(filter_size, (list, tuple)) else (
        int(filter_size), int(filter_size))
    c_in = int(x.shape[1])
    w = create_parameter([num_filters, c_in // groups, ks[0], ks[1]],
                         "float32", attr=param_attr)
    b = (create_parameter([num_filters], "float32", attr=bias_attr,
                          is_bias=True)
         if bias_attr is not False else None)
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


__all__ += ["deform_conv2d"]


def var_conv_2d(x, row, col, input_channel, output_channel, filter_size,
                stride=1, w=None, param_attr=None, act=None):
    """Variable-size 2-D convolution over LoD images (reference:
    var_conv_2d_op.cc): each sample i carries its own (H_i, W_i) given by
    the ROW/COLUMN LoD inputs; x is the flat concatenation of
    [C, H_i, W_i] images. Output spatial size per sample is
    (H_i-1)//stride_h+1 x (W_i-1)//stride_w+1 (SAME-style).

    TPU framing: per-sample shapes are DATA, so samples convolve
    individually on the tape (gradients flow to the shared filter `w`);
    returns a list of per-sample [out_c, oh_i, ow_i] Tensors (the
    reference returns the re-flattened LoD tensor; use
    static.array_to_lod_tensor on the result for that form).
    """
    import numpy as np

    from ..framework.lod import LoDTensor
    from ..framework.tensor import Tensor, create_parameter, to_tensor

    kh, kw = (filter_size if isinstance(filter_size, (list, tuple))
              else (filter_size, filter_size))
    sh, sw = (stride if isinstance(stride, (list, tuple))
              else (stride, stride))

    def lens_of(v):
        if isinstance(v, LoDTensor):
            return v.innermost_lengths()
        return [int(n) for n in np.asarray(
            v.numpy() if isinstance(v, Tensor) else v).reshape(-1)]

    heights = lens_of(row)
    widths = lens_of(col)
    if len(heights) != len(widths):
        raise ValueError(
            f"ROW has {len(heights)} samples but COLUMN {len(widths)}")
    expected = sum(input_channel * h * wd
                   for h, wd in zip(heights, widths))
    if w is None:
        w = create_parameter(
            [output_channel, input_channel * kh * kw], "float32",
            attr=param_attr)
    wt = w.reshape([output_channel, input_channel, kh, kw])

    if isinstance(x, LoDTensor):
        flat = np.asarray(x.numpy()).reshape(-1)
        if flat.size != expected:
            raise ValueError(
                f"x has {flat.size} elements but ROW/COLUMN imply "
                f"{expected} (= sum C*H_i*W_i)")
        samples = []
        off = 0
        for h, wd in zip(heights, widths):
            n = input_channel * h * wd
            samples.append(to_tensor(
                flat[off:off + n].reshape(1, input_channel, h, wd)
                .astype(np.float32)))
            off += n
    else:
        if len(x) != len(heights):
            raise ValueError(
                f"x has {len(x)} samples but ROW/COLUMN {len(heights)}")
        samples = [s if isinstance(s, Tensor) else to_tensor(np.asarray(s))
                   for s in x]
        samples = [s.reshape([1, input_channel, h, wd])
                   for s, h, wd in zip(samples, heights, widths)]

    import paddle_tpu.nn.functional as F

    outs = []
    for s, h, wd in zip(samples, heights, widths):
        # the reference im2col CENTERS the window: pad_low = k//2 on each
        # side (var_conv_2d_op.cc im_y = y + ky - kernel_h/2); XLA SAME
        # pads low = total//2 which differs when the total pad is odd —
        # pass explicit per-side padding instead
        oh = (h - 1) // sh + 1
        ow = (wd - 1) // sw + 1
        pt = kh // 2
        pl = kw // 2
        pb = max(0, (oh - 1) * sh + kh - h - pt)
        pr = max(0, (ow - 1) * sw + kw - wd - pl)
        o = F.conv2d(s, wt, stride=(sh, sw), padding=[[pt, pb], [pl, pr]])
        if act:
            o = getattr(F, act)(o)
        outs.append(o[0])
    return outs


__all__ += ["var_conv_2d"]

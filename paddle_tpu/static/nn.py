"""paddle.static.nn — layer-builder functions for static graphs.

Parity with python/paddle/static/nn/ (fc, conv2d, batch_norm, embedding, …):
each call builds the matching paddle_tpu.nn layer (parameters are created and
registered on the active Program so they survive as tape externals) and
applies it, so the ops land on the Program tape.
"""
from __future__ import annotations

__all__ = ["fc", "embedding", "conv2d", "batch_norm", "layer_norm", "dropout"]


def _keep(layer):
    from . import _current_program

    _current_program()._layers.append(layer)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully connected: dims [num_flatten_dims:] contract against the weight,
    dims [:num_flatten_dims] stay (reference static.nn.fc semantics)."""
    from .. import nn

    nfd = num_flatten_dims
    shape = [int(d) for d in x.shape]
    in_f = 1
    for d in shape[nfd:]:
        in_f *= d
    if shape[nfd:] != [in_f]:
        # collapse the contracted dims; keep dims [:nfd] (batch dim dynamic)
        x = x.reshape([-1] + shape[1:nfd] + [in_f])
    layer = _keep(nn.Linear(in_f, size, weight_attr=weight_attr,
                            bias_attr=bias_attr, name=name))
    out = layer(x)  # Linear contracts the last dim, keeping leading dims
    if activation:
        import paddle_tpu.nn.functional as F

        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              weight_attr=None, name=None):
    from .. import nn

    layer = _keep(nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                               weight_attr=weight_attr, name=name))
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from .. import nn

    in_c = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _keep(nn.Conv2D(in_c, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from .. import nn

    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = _keep(nn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                                 weight_attr=param_attr, bias_attr=bias_attr,
                                 data_format=data_layout))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn

    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    layer = _keep(nn.LayerNorm(shape, epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    import paddle_tpu.nn.functional as F

    return F.dropout(x, p=dropout_prob, training=not is_test)


# sequence op family (reference: paddle.static.nn.sequence_* over
# fluid/operators/sequence_ops/; padded+lengths carrier — see
# nn/functional/sequence.py)
from ..nn.functional.sequence import (  # noqa: F401,E402
    sequence_concat, sequence_expand, sequence_first_step, sequence_last_step,
    sequence_mask, sequence_pad, sequence_pool, sequence_reverse,
    sequence_slice, sequence_softmax, sequence_unpad,
)

"""paddle.static.nn — layer-builder functions for static graphs.

Parity with python/paddle/static/nn/ (fc, conv2d, batch_norm, embedding, …):
each call builds the matching paddle_tpu.nn layer (parameters are created and
registered on the active Program so they survive as tape externals) and
applies it, so the ops land on the Program tape.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fc", "embedding", "conv2d", "batch_norm", "layer_norm", "dropout"]


def _keep(layer):
    from . import _current_program

    _current_program()._layers.append(layer)
    return layer


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully connected: dims [num_flatten_dims:] contract against the weight,
    dims [:num_flatten_dims] stay (reference static.nn.fc semantics)."""
    from .. import nn

    nfd = num_flatten_dims
    shape = [int(d) for d in x.shape]
    in_f = 1
    for d in shape[nfd:]:
        in_f *= d
    if shape[nfd:] != [in_f]:
        # collapse the contracted dims; keep dims [:nfd] (batch dim dynamic)
        x = x.reshape([-1] + shape[1:nfd] + [in_f])
    layer = _keep(nn.Linear(in_f, size, weight_attr=weight_attr,
                            bias_attr=bias_attr, name=name))
    out = layer(x)  # Linear contracts the last dim, keeping leading dims
    if activation:
        import paddle_tpu.nn.functional as F

        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              weight_attr=None, name=None):
    from .. import nn

    layer = _keep(nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                               weight_attr=weight_attr, name=name))
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from .. import nn

    in_c = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _keep(nn.Conv2D(in_c, num_filters, filter_size, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from .. import nn

    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = _keep(nn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                                 weight_attr=param_attr, bias_attr=bias_attr,
                                 data_format=data_layout))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn

    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    layer = _keep(nn.LayerNorm(shape, epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr))
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F

        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    import paddle_tpu.nn.functional as F

    return F.dropout(x, p=dropout_prob, training=not is_test)


# sequence op family (reference: paddle.static.nn.sequence_* over
# fluid/operators/sequence_ops/; padded+lengths carrier — see
# nn/functional/sequence.py)
from ..nn.functional.sequence import (  # noqa: F401,E402
    sequence_concat, sequence_expand, sequence_first_step, sequence_last_step,
    sequence_mask, sequence_pad, sequence_pool, sequence_reverse,
    sequence_slice, sequence_softmax, sequence_unpad,
)


# --------------------------------------------------------------------------
# control-flow ops (reference: operators/controlflow/ while_op.cc,
# conditional_block_op.cc; python API paddle.static.nn.cond/while_loop/
# case/switch_case in python/paddle/fluid/layers/control_flow.py)
# --------------------------------------------------------------------------

def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Run ``body`` while ``cond(*loop_vars)`` holds, as ONE structured op.

    TPU-native: records a single tape op whose kernel is
    ``jax.lax.while_loop`` — the XLA analog of the reference's while_op
    block (operators/controlflow/while_op.cc). The trip count stays
    data-dependent at runtime (it is NOT baked at Program-build time).

    Like ``jax.lax.while_loop``, the op has no reverse-mode gradient; the
    loop runs under no_grad and its outputs carry stop_gradient=True (the
    reference's while grad op has no XLA equivalent).

    ``cond``/``body`` may reference other tensors from the enclosing scope;
    DIRECT references (closure cells, module globals, functools.partial
    args, a bound method's self/closure) are captured as implicit op inputs
    so Program replay sees live feed values. A tensor reached only through a
    helper function the branch calls is NOT discoverable — pass it via
    ``loop_vars`` instead.
    """
    import functools

    import jax

    from ..framework.autograd import call_op, no_grad
    from ..framework.tensor import Tensor

    flat = list(loop_vars)
    if not flat:
        raise ValueError("loop_vars must be non-empty")
    for v in flat:
        if not isinstance(v, Tensor):
            raise TypeError("while_loop loop_vars must be Tensors "
                            f"(got {type(v).__name__})")
    protos = flat

    # Tensors captured in cond/body closure cells (e.g. a fed `n` bound in
    # `lambda i, a: i < n`) become implicit op inputs, so Program replay
    # substitutes the live feed value instead of the build-time placeholder
    # (the reference wires these as while-block inputs the same way).
    captured = []
    seen = {id(p) for p in protos}

    def _capture(c):
        items = c if isinstance(c, (list, tuple)) else [c]
        for it in items:
            if isinstance(it, Tensor) and id(it) not in seen:
                seen.add(id(it))
                captured.append(it)

    def _scan_fn(f, depth=0):
        if depth > 2:
            return
        if isinstance(f, functools.partial):
            _capture(list(f.args) + list(f.keywords.values()))
            _scan_fn(f.func, depth + 1)
            return
        if hasattr(f, "__func__"):  # bound method: scan self attrs too
            self_obj = getattr(f, "__self__", None)
            if self_obj is not None:
                _capture([v for v in getattr(self_obj, "__dict__",
                                             {}).values()
                          if isinstance(v, Tensor)])
            _scan_fn(f.__func__, depth + 1)
            return
        for cell in (getattr(f, "__closure__", None) or ()):
            try:
                _capture(cell.cell_contents)
            except ValueError:
                continue
        # module-level scripts bind outer tensors as globals, not cells
        code = getattr(f, "__code__", None)
        if code is not None:
            for nm in code.co_names:
                if nm in getattr(f, "__globals__", {}):
                    _capture(f.__globals__[nm])

    for f in (cond, body):
        _scan_fn(f)
    n_loop = len(flat)

    def _wrap(vals):
        out = []
        for v, p in zip(vals, protos):
            t = Tensor(v, _internal=True)
            t.stop_gradient = True
            out.append(t)
        return tuple(out)

    def _unwrap(out):
        seq = out if isinstance(out, (list, tuple)) else [out]
        if len(seq) != len(protos):
            raise ValueError(
                f"body returned {len(seq)} values; expected {len(protos)}")
        return tuple(jnp.asarray(o._value if isinstance(o, Tensor) else o)
                     for o in seq)

    def fn(*vals):
        from ..framework import autograd as _ag

        loop_vals, clos_vals = vals[:n_loop], vals[n_loop:]

        def _paused(thunk):
            # inner ops run on while tracers: they must not land on the
            # Program tape (only the outer while op is the recorded node)
            prev = _ag.set_op_recorder(None)
            old = [t._value for t in captured]
            for t, v in zip(captured, clos_vals):
                t._value = v
            try:
                with no_grad():
                    return thunk()
            finally:
                for t, v in zip(captured, old):
                    t._value = v
                _ag.set_op_recorder(prev)

        def c(vs):
            r = _paused(lambda: cond(*_wrap(vs)))
            r = r._value if isinstance(r, Tensor) else r
            return jnp.asarray(r).astype(bool).reshape(())

        def b(vs):
            return _paused(lambda: _unwrap(body(*_wrap(vs))))

        return jax.lax.while_loop(
            c, b, tuple(jnp.asarray(v) for v in loop_vals))

    with no_grad():  # lax.while_loop has no reverse-mode derivative
        out = call_op(fn, *flat, *captured, op_name="while_loop")
    out = out if isinstance(out, (list, tuple)) else [out]
    for t in out:
        t.stop_gradient = True
    return list(out)


def _select_outputs(pred, a_out, b_out, op_label):
    """Elementwise select between two same-structure branch outputs."""
    from ..framework.autograd import call_op
    from ..framework.tensor import Tensor

    seq_a = a_out if isinstance(a_out, (list, tuple)) else [a_out]
    seq_b = b_out if isinstance(b_out, (list, tuple)) else [b_out]
    if len(seq_a) != len(seq_b):
        raise ValueError(
            f"{op_label}: branches returned {len(seq_a)} vs {len(seq_b)} "
            "outputs; structures must match")
    outs = []
    for a, b in zip(seq_a, seq_b):
        if not isinstance(a, Tensor) or not isinstance(b, Tensor):
            raise TypeError(f"{op_label}: branch outputs must be Tensors")

        def fn(p, av, bv):
            return jnp.where(jnp.asarray(p).astype(bool).reshape(()), av, bv)

        outs.append(call_op(fn, pred, a, b, op_name=op_label))
    if not isinstance(a_out, (list, tuple)):
        return outs[0]
    return type(a_out)(outs) if isinstance(a_out, tuple) else outs


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Two-way branch on a boolean tensor (reference:
    conditional_block_op.cc; API control_flow.py cond).

    TPU-native semantics: BOTH branches execute and a select picks the
    result per element of the predicate's truth value — XLA's select
    idiom, correct (and differentiable) for the side-effect-free branch
    functions the static API requires. Branch outputs must match in
    structure, shape and dtype (the reference shares this constraint).
    """
    from ..framework.tensor import Tensor

    if true_fn is None or false_fn is None:
        raise ValueError("cond requires both true_fn and false_fn")
    if not isinstance(pred, Tensor):
        import numpy as _np

        return true_fn() if bool(_np.asarray(pred)) else false_fn()
    return _select_outputs(pred, true_fn(), false_fn(), "cond")


def case(pred_fn_pairs, default=None, name=None):
    """First-match multi-way branch (reference: control_flow.py case)."""
    pred_fn_pairs = list(pred_fn_pairs)
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    if default is None:
        # reference semantics: the last pair's fn doubles as the default
        default = pred_fn_pairs[-1][1]
    out = default()
    # evaluate in reverse: earlier predicates take precedence
    for pred, fn in reversed(list(pred_fn_pairs)):
        out = _select_outputs(pred, fn(), out, "case")
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer-indexed branch (reference: control_flow.py switch_case)."""
    from ..framework.autograd import call_op

    if isinstance(branch_fns, dict):
        items = list(branch_fns.items())
    else:
        seq = list(branch_fns)
        # both forms the reference accepts: [fn, ...] and [(index, fn), ...]
        if seq and isinstance(seq[0], (tuple, list)):
            items = [(int(i), f) for i, f in seq]
        else:
            items = list(enumerate(seq))
    if default is None:
        default = items[-1][1]
    out = default()
    for idx, fn in reversed(items):
        def eq(bi, _i=int(idx)):
            return (jnp.asarray(bi).reshape(()) == _i)

        pred = call_op(eq, branch_index, op_name="switch_case_eq")
        out = _select_outputs(pred, fn(), out, "switch_case")
    return out


__all__ += ["while_loop", "cond", "case", "switch_case"]

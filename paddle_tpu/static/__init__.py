"""paddle_tpu.static — static-graph compatibility facade.

Capability parity with the reference's static mode (python/paddle/static/,
fluid/framework.py Program/Block, fluid/executor.py Executor.run §3.1), built
the TPU way per SURVEY.md §7: building a Program *records* every dispatched
functional kernel onto a tape (the ProgramDesc analog), and `Executor.run`
replays the tape as one pure function compiled by XLA — the interpreter hot
loop of the reference (executor.cc:424) becomes a single jitted program.

Training: `optimizer.minimize(loss)` under static mode registers the optimizer
on the program; `Executor.run` then compiles forward+backward+update into one
donated-buffer XLA step (grads via jax.grad instead of append_backward's grad-
op emission — backward.py:— in the reference).
"""
from __future__ import annotations

import contextlib
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import autograd
from ..framework import dtype as dtype_mod
from ..framework.tensor import Parameter, Tensor
from . import nn  # noqa: F401

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "InputSpec", "Executor", "scope_guard",
    "global_scope", "append_backward", "gradients", "CompiledProgram",
    "BuildStrategy", "ExecutionStrategy", "save", "load", "set_program_state",
    "cpu_places", "cuda_places", "tpu_places", "name_scope", "device_guard",
    "py_func", "Variable", "save_inference_model", "load_inference_model",
    "InferenceProgram",
]

Variable = Tensor  # static Variables are Tensors carrying a tape var id

_all_programs: list = []  # weakrefs; global_scope() name lookup walks these


class _OpRecord:
    __slots__ = ("fn", "arg_spec", "kwargs", "out_ids", "multi", "name")

    def __init__(self, fn, arg_spec, kwargs, out_ids, multi, name):
        self.fn = fn
        self.arg_spec = arg_spec  # list of ("var", id) | ("const", value)
        self.kwargs = kwargs
        self.out_ids = out_ids
        self.multi = multi
        self.name = name


class Program:
    """Recorded op tape + variable registry (ProgramDesc analog,
    framework/framework.proto:234)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self._id = Program._counter
        self.ops: List[_OpRecord] = []
        self._next_var = 0
        self.feeds: Dict[str, int] = {}       # feed name → var id
        self.feed_shapes: Dict[str, tuple] = {}
        self.feed_dtypes: Dict[str, Any] = {}
        self.externals: Dict[int, Tensor] = {}  # var id → live Tensor (scope)
        self.feed_tensors: Dict[int, Tensor] = {}  # var id → placeholder
        self.var_names: Dict[str, int] = {}   # fetchable names → var id
        self._train = None                    # (optimizer, loss var id)
        self._loss_id = None                  # set by append_backward
        self._grad_params: List[Tensor] = []  # params whose @GRAD is fetchable
        self._layers: list = []               # keep nn layers built inside alive
        self.random_seed = 0
        self._for_test = False
        _all_programs.append(weakref.ref(self))

    # -- recording ----------------------------------------------------------
    def _new_var(self):
        self._next_var += 1
        return self._next_var

    def _tape_id_of(self, t: Tensor):
        """Resolve a tensor's tape id on this program, falling back to the
        program(s) this one was cloned from (vids are shared at clone time)."""
        ids = getattr(t, "_tape_ids", {})
        vid = ids.get(self._id)
        if vid is None:
            for origin in getattr(self, "_origin_ids", ()):
                vid = ids.get(origin)
                if vid is not None:
                    break
        return vid

    def _var_of(self, t: Tensor):
        """Tape id for an input tensor; unseen tensors become externals
        (parameters, constants created at build time — the Scope analog)."""
        vid = self._tape_id_of(t)
        if vid is None:
            vid = self._new_var()
            ids = getattr(t, "_tape_ids", None)
            if ids is None:
                ids = {}
                object.__setattr__(t, "_tape_ids", ids)
            ids[self._id] = vid
            self.externals[vid] = t
            name = getattr(t, "name", None)
            if not name and isinstance(t, Parameter):
                # deterministic per-build name (unique_name analog) so
                # static.save/load keys are stable across identical builds
                name = f"param_{vid}"
                t.name = name
            if name:
                self.var_names.setdefault(name, vid)
        return vid

    def _record(self, fn, args, kwargs, outputs, op_name):
        arg_spec = []
        for a in args:
            if isinstance(a, Tensor):
                arg_spec.append(("var", self._var_of(a)))
            else:
                arg_spec.append(("const", a))
        outs = outputs if isinstance(outputs, tuple) else (outputs,)
        out_ids = []
        for o in outs:
            vid = self._new_var()
            ids = getattr(o, "_tape_ids", None)
            if ids is None:
                ids = {}
                object.__setattr__(o, "_tape_ids", ids)
            ids[self._id] = vid
            out_ids.append(vid)
            name = getattr(o, "name", None)
            if name:
                self.var_names[name] = vid
        self.ops.append(_OpRecord(fn, arg_spec, dict(kwargs), out_ids,
                                  isinstance(outputs, tuple),
                                  op_name or getattr(fn, "__name__", "op")))

    # -- program API parity --------------------------------------------------
    def global_block(self):
        return self

    def var(self, name):
        vid = self.var_names.get(name)
        if vid is None:
            raise ValueError(f"variable {name!r} not found in program")
        t = self.externals.get(vid)  # no `or`: Tensor.__bool__ is elementwise
        return t if t is not None else self.feed_tensors.get(vid)

    def all_parameters(self):
        return [t for t in self.externals.values()
                if isinstance(t, Parameter)]

    def list_vars(self):
        return list(self.externals.values())

    def to_string(self, throw_on_error=False, with_details=False):
        """Human-readable op/var listing (reference: Program.to_string,
        fluid/framework.py — the ProgramDesc debug print)."""
        id2name = {vid: nm for nm, vid in self.var_names.items()}
        id2name.update({vid: nm for nm, vid in self.feeds.items()})
        lines = [f"program id={self._id} ops={len(self.ops)} "
                 f"feeds={list(self.feeds)} params="
                 f"{len(self.all_parameters())}"]
        for k, op in enumerate(self.ops):
            ins = [id2name.get(a[1], f"v{a[1]}") if a[0] == "var"
                   else repr(a[1])[:20] for a in op.arg_spec]
            outs = [id2name.get(o, f"v{o}") for o in op.out_ids]
            lines.append(f"  {{Op({k}) {op.name or op.fn.__name__}: "
                         f"({', '.join(ins)}) -> ({', '.join(outs)})}}")
        return "\n".join(lines)

    def clone(self, for_test=False):
        import copy

        p = copy.copy(self)
        Program._counter += 1
        p._id = Program._counter  # fresh identity: no vid collisions with us
        p._origin_ids = (self._id,) + tuple(getattr(self, "_origin_ids", ()))
        p.ops = list(self.ops)
        p.externals = dict(self.externals)
        p.var_names = dict(self.var_names)
        p.feeds = dict(self.feeds)
        p.feed_tensors = dict(self.feed_tensors)
        p._layers = list(self._layers)
        p._for_test = for_test
        if for_test:
            p._train = None
        return p

    def __str__(self):
        return self.to_string()


_default_main = Program()
_default_startup = Program()
_prog_stack: List[tuple] = []


def default_main_program():
    return _prog_stack[-1][0] if _prog_stack else _default_main


def default_startup_program():
    return _prog_stack[-1][1] if _prog_stack else _default_startup


def _current_program():
    return default_main_program()


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _prog_stack.append((main_program,
                        startup_program or default_startup_program()))
    prev = autograd.set_op_recorder(_recorder)
    try:
        yield
    finally:
        _prog_stack.pop()
        autograd.set_op_recorder(prev)


def _recorder(fn, args, kwargs, outputs, op_name):
    _current_program()._record(fn, args, kwargs, outputs, op_name)


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable. The placeholder carries zeros with dynamic
    dims (None/-1) set to 1; real shapes come from the feed at run time."""
    prog = _current_program()
    build_shape = tuple(1 if (d is None or d < 0) else int(d) for d in shape)
    t = Tensor(jnp.zeros(build_shape,
                         dtype=dtype_mod.convert_dtype(dtype)),
               _internal=True)
    t.stop_gradient = True
    t.name = name
    vid = prog._new_var()
    ids = {}
    object.__setattr__(t, "_tape_ids", ids)
    ids[prog._id] = vid
    prog.feeds[name] = vid
    prog.feed_tensors[vid] = t
    prog.feed_shapes[name] = tuple(shape)
    prog.feed_dtypes[name] = dtype
    prog.var_names[name] = vid
    return t


class InputSpec:
    """Shape/dtype spec (parity: paddle/static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype!r}, "
                f"name={self.name!r})")


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Register the loss for gradient computation; returns (param, grad-ref)
    pairs whose grad refs can be fetched from Executor.run (replacement for
    grad-op emission, fluid/backward.py append_backward)."""
    prog = _current_program()
    prog._loss_id = prog._var_of(loss)
    params = parameter_list or [
        t for t in prog.externals.values()
        if isinstance(t, Parameter) and not t.stop_gradient
    ]
    prog._grad_params = list(params)
    pairs = []
    for p in params:
        ref = _GradRef(p)
        pairs.append((p, ref))
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(sum of targets)/d(inputs) as fetchable refs (static backward.py
    gradients). Inputs may be any tape variables, not just Parameters."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) > 1 or target_gradients is not None:
        raise NotImplementedError(
            "multiple targets / custom target_gradients: sum the targets "
            "into one loss instead")
    append_backward(targets[0], parameter_list=[
        x for x in inputs if isinstance(x, Parameter)] or None)
    prog = _current_program()
    refs = []
    for x in inputs:
        if isinstance(x, Parameter):
            refs.append(_GradRef(x))
        else:
            refs.append(_GradVarRef(x, prog._var_of(x)))
    return refs


class _GradRef:
    """Fetchable handle for a parameter's gradient (`w@GRAD` analog)."""

    def __init__(self, param):
        self.param = param
        self.name = f"{getattr(param, 'name', 'param')}@GRAD"


class _GradVarRef:
    """Fetchable handle for d(loss)/d(arbitrary tape var), e.g. x@GRAD."""

    def __init__(self, tensor, vid):
        self.tensor = tensor
        self.vid = vid
        self.name = f"{getattr(tensor, 'name', 'var')}@GRAD"


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class _Scope:
    def __init__(self):
        self._vars = {}

    def find_var(self, name):
        progs = [_default_main] + [p for p, _ in _prog_stack] + [
            p for r in _all_programs if (p := r()) is not None]
        for prog in progs:
            try:
                t = prog.var(name)
            except ValueError:
                continue
            if t is not None:
                return _ScopeVar(t)
        return self._vars.get(name)

    def var(self, name):
        v = self.find_var(name)
        if v is None:
            v = _ScopeVar(None)
            self._vars[name] = v
        return v


class _ScopeVar:
    def __init__(self, t):
        self._t = t

    def get_tensor(self):
        return self._t.numpy() if self._t is not None else None


_global_scope = _Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    yield scope


class Executor:
    """Replay a Program as one compiled XLA callable (§3.1's Executor.run)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}

    def close(self):
        self._cache.clear()

    # -- fetch resolution ----------------------------------------------------
    @staticmethod
    def _fetch_ids(program, fetch_list):
        ids = []
        for f in fetch_list or []:
            if isinstance(f, _GradVarRef):
                ids.append(("gradvar", f.vid))
            elif isinstance(f, _GradRef):
                ids.append(("grad", f.param))
            elif isinstance(f, Tensor):
                vid = program._tape_id_of(f)
                if vid is None:
                    vid = program._var_of(f)
                ids.append(("var", vid))
            elif isinstance(f, str):
                name = f.split("@GRAD")[0] if f.endswith("@GRAD") else f
                if f.endswith("@GRAD"):
                    for p in program._grad_params:
                        if getattr(p, "name", None) == name:
                            ids.append(("grad", p))
                            break
                    else:
                        raise ValueError(f"no grad recorded for {name!r}")
                else:
                    ids.append(("var", program.var_names[f]))
            else:
                raise TypeError(f"unsupported fetch entry {f!r}")
        return ids

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program or default_main_program()
        feed = feed or {}
        if isinstance(program, InferenceProgram):
            return program.run(feed, fetch_list)
        if not program.ops:
            return []  # startup program: initializers already ran eagerly

        unknown = set(feed) - set(program.feeds)
        if unknown:
            raise ValueError(
                f"feed entries {sorted(unknown)} are not data() variables of "
                f"this program (declared: {sorted(program.feeds)})")
        feed_names = [n for n in program.feeds if n in feed]
        # feeds actually consumed by the tape must all be provided
        used_vids = {s[1] for rec in program.ops for s in rec.arg_spec
                     if s[0] == "var"}
        missing = [n for n, vid in program.feeds.items()
                   if vid in used_vids and n not in feed]
        if missing:
            raise ValueError(f"program consumes feed variables {missing} "
                             "but they were not fed")
        feed_vals = [jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        fetch_ids = self._fetch_ids(program, fetch_list)

        # externals, split into trainable params vs the rest
        ext_ids = sorted(program.externals)
        train = program._train
        need_grads = any(k in ("grad", "gradvar") for k, _ in fetch_ids) \
            or train
        if need_grads:
            gparams = (program._grad_params or
                       [t for t in program.externals.values()
                        if isinstance(t, Parameter) and not t.stop_gradient])
        else:
            gparams = []
        gparam_ids = {id(p) for p in gparams}
        p_ids = [vid for vid in ext_ids
                 if id(program.externals[vid]) in gparam_ids]
        o_ids = [vid for vid in ext_ids
                 if id(program.externals[vid]) not in gparam_ids]

        key = (program._id, len(program.ops), tuple(feed_names),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple((k, id(v)) if k == "grad" else (k, v)
                     for k, v in fetch_ids),
               bool(train), tuple(p_ids))
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            entry = self._compile(program, feed_names, fetch_ids, p_ids,
                                  o_ids, bool(train))
            if use_program_cache:
                self._cache[key] = entry
        fn = entry

        p_tensors = [program.externals[vid] for vid in p_ids]
        o_tensors = [program.externals[vid] for vid in o_ids]
        pvals = [t._value for t in p_tensors]
        ovals = [t._value for t in o_tensors]

        if train:
            opt, loss_vid = program._train
            slots = []
            for p in p_tensors:
                if id(p) not in opt._slots:
                    opt._slots[id(p)] = opt._init_slots(p._value)
                slots.append(opt._slots[id(p)])
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            new_p, new_s, fetches = fn(pvals, slots, lr, feed_vals, ovals)
            for p, npv, nsv in zip(p_tensors, new_p, new_s):
                p._value = npv
                opt._slots[id(p)] = nsv
            opt._accumulated_steps += 1
            mark = getattr(opt, "_mark_slot_writer", None)
            if mark is not None:  # static writes land in _slots directly
                mark("eager")     # (same store the eager path owns)
            sched = getattr(opt, "_learning_rate", None)
            if hasattr(sched, "step") and not isinstance(sched, (int, float)):
                pass  # LR scheduling stays user-driven, as in dygraph
        else:
            fetches = fn(pvals, feed_vals, ovals)

        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return [Tensor(v, _internal=True) for v in fetches]

    # -- compilation ---------------------------------------------------------
    def _compile(self, program, feed_names, fetch_ids, p_ids, o_ids, train):
        feed_vids = [program.feeds[n] for n in feed_names]

        def replay(env, overrides=None):
            # overrides: vid → value forced in place of the produced/bound
            # value (differentiation wrt intermediates: static.gradients)
            if overrides:
                for vid, v in overrides.items():
                    if vid in env:
                        env[vid] = v
            for rec in program.ops:
                ins = [env[s[1]] if s[0] == "var" else s[1]
                       for s in rec.arg_spec]
                out = rec.fn(*ins, **rec.kwargs)
                if rec.multi:
                    for oid, o in zip(rec.out_ids, out):
                        env[oid] = o
                else:
                    env[rec.out_ids[0]] = out
                if overrides:
                    for oid in rec.out_ids:
                        if oid in overrides:
                            env[oid] = overrides[oid]
            return env

        def bind(pvals, feed_vals, ovals):
            env = {}
            for vid, v in zip(p_ids, pvals):
                env[vid] = v
            for vid, v in zip(o_ids, ovals):
                env[vid] = v
            for vid, v in zip(feed_vids, feed_vals):
                env[vid] = v
            return env

        # grads come back aligned with pvals, i.e. in p_ids (var-id) order
        gp_pos = {id(program.externals[vid]): i for i, vid in enumerate(p_ids)}
        gv_vids = [ref for kind, ref in fetch_ids if kind == "gradvar"]

        def collect(env, grads, var_grads=None):
            out = []
            for kind, ref in fetch_ids:
                if kind == "grad":
                    out.append(grads[gp_pos[id(ref)]])
                elif kind == "gradvar":
                    out.append(var_grads[gv_vids.index(ref)])
                else:
                    out.append(env[ref])
            return out

        need_grads = any(k in ("grad", "gradvar") for k, _ in fetch_ids)

        if not train:
            if need_grads:
                loss_vid = program._loss_id

                def fn(pvals, feed_vals, ovals):
                    # forward pass to materialize values of the grad targets
                    env0 = replay(bind(pvals, feed_vals, ovals))
                    sel0 = [env0[vid] for vid in gv_vids]

                    def loss_of(pv, sel):
                        env = replay(bind(pv, feed_vals, ovals),
                                     dict(zip(gv_vids, sel)))
                        return env[loss_vid], env

                    (gp, gv), env = jax.grad(
                        loss_of, argnums=(0, 1), has_aux=True)(pvals, sel0)
                    return collect(env, gp, gv)

                return jax.jit(fn)

            def fn(pvals, feed_vals, ovals):
                env = replay(bind(pvals, feed_vals, ovals))
                return collect(env, None)

            return jax.jit(fn)

        opt, loss_vid = program._train

        def train_fn(pvals, slots, lr, feed_vals, ovals):
            if gv_vids:
                env0 = replay(bind(pvals, feed_vals, ovals))
                sel0 = [env0[vid] for vid in gv_vids]
            else:
                sel0 = []

            def loss_of(pv, sel):
                env = replay(bind(pv, feed_vals, ovals),
                             dict(zip(gv_vids, sel)))
                return env[loss_vid], env

            (grads, gv), env = jax.grad(
                loss_of, argnums=(0, 1), has_aux=True)(pvals, sel0)
            clip_cfg = opt._clip_cfg()
            if clip_cfg is not None:
                from ..jit import _apply_clip

                grads = _apply_clip(grads, clip_cfg)
            new_p, new_s = opt.apply_gradients_tree(pvals, grads, slots, lr)
            # donated-buffer outputs (new_p, new_s pair with the donated
            # slots) come BEFORE the fetches: a fetched gradient is
            # param-shaped and would otherwise steal the donation alias
            # slot (rule D002 — the PR-8 TrainStep bug, same shape)
            return new_p, new_s, collect(env, grads, gv)

        return jax.jit(train_fn, donate_argnums=(1,))

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive training straight from a fleet Dataset (reference:
        executor.py train_from_dataset → Trainer/DeviceWorker/DataFeed C++
        pipeline). TPU-native: the dataset's slot batches feed the compiled
        program in feed-declaration order; the C++ ingestion pipeline role is
        played by the dataset's pipe_command + the multiprocess DataLoader
        machinery."""
        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        program = program or default_main_program()
        feed_names = list(program.feeds)
        it = 0
        last = []
        for batch in dataset.iterate():
            if len(batch) != len(feed_names):
                raise ValueError(
                    f"dataset yields {len(batch)} slots but the program "
                    f"declares {len(feed_names)} feeds {feed_names}")
            feed = {n: np.asarray(v) for n, v in zip(feed_names, batch)}
            last = self.run(program, feed=feed, fetch_list=fetch_list)
            if debug and fetch_list and it % print_period == 0:
                names = fetch_info or [f"fetch{i}"
                                       for i in range(len(last))]
                print(f"[train_from_dataset] iter {it}: " + ", ".join(
                    f"{n}={np.asarray(v).ravel()[:1]}"
                    for n, v in zip(names, last)))
            it += 1
        return last

    def infer_from_dataset(self, program=None, dataset=None, **kw):
        """Evaluation twin of train_from_dataset (executor.py:infer_from_
        dataset): same drive loop over a program without an optimizer."""
        return self.train_from_dataset(program, dataset, **kw)


# ---------------------------------------------------------------------------
# inference model save/load (reference: python/paddle/static/io.py
# save_inference_model/load_inference_model; consumed by the
# AnalysisPredictor stack). Format: inference/io.py StableHLO triple.
# ---------------------------------------------------------------------------

class _FetchTarget:
    """Opaque fetch handle returned by load_inference_model."""

    def __init__(self, index: int):
        self.index = index

    def __repr__(self):
        return f"FetchTarget({self.index})"


class InferenceProgram:
    """Loaded inference artifact masquerading as a Program for Executor.run
    (the reference's returned inference_program)."""

    def __init__(self, artifact):
        self.artifact = artifact
        self.feed_names = list(artifact.feed_names)
        self.fetch_targets = [_FetchTarget(i)
                              for i in range(artifact.n_fetches)]
        self.ops = []  # Program-duck-typing

    def run(self, feed: Dict[str, Any], fetch_list=None):
        vals = [feed[n] for n in self.feed_names]
        outs = self.artifact.run(vals)
        if fetch_list:
            outs = [outs[f.index if isinstance(f, _FetchTarget) else int(f)]
                    for f in fetch_list]
        return [np.asarray(o) for o in outs]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize program slice feed_vars -> fetch_vars for deployment.

    Writes <prefix>.pdmodel (StableHLO), <prefix>.pdiparams (weights),
    <prefix>.manifest.json — loadable by static.load_inference_model and by
    paddle_tpu.inference.create_predictor in a fresh process.
    """
    from ..inference.io import export_inference_artifact

    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]

    id_to_name = {vid: n for n, vid in program.feeds.items()}
    feed_specs = []
    feed_vids = []
    for t in feed_vars:
        vid = program._tape_id_of(t)
        if vid is None or vid not in id_to_name:
            raise ValueError("feed_vars must be static.data() variables of "
                             "this program")
        feed_vids.append(vid)
        name = id_to_name[vid]
        declared = program.feed_shapes.get(name)
        shape = (tuple(None if d is None or (isinstance(d, int) and d < 0)
                       else int(d) for d in declared)
                 if declared is not None
                 else tuple(int(d) for d in t._value.shape))
        feed_specs.append((name, shape, str(t._value.dtype)))
    fetch_vids = []
    for t in fetch_vars:
        vid = program._tape_id_of(t)
        if vid is None:
            raise ValueError("fetch_vars must be outputs of this program")
        fetch_vids.append(vid)

    # backward slice: keep only ops the fetches depend on (the reference's
    # prune() of the inference program — unfed branches like the loss drop)
    needed = set(fetch_vids)
    kept = []
    for rec in reversed(program.ops):
        if any(oid in needed for oid in rec.out_ids):
            kept.append(rec)
            needed.update(s[1] for s in rec.arg_spec if s[0] == "var")
    kept.reverse()

    ext_ids = [vid for vid in sorted(program.externals) if vid in needed]
    weight_vals = [program.externals[vid]._value for vid in ext_ids]
    unfed = needed - set(ext_ids) - set(feed_vids) - {
        oid for rec in kept for oid in rec.out_ids}
    if unfed:
        raise ValueError(
            f"fetch_vars depend on un-fed variables {sorted(unfed)}; add the "
            "corresponding data() vars to feed_vars")

    def fn(ws, fs):
        env = dict(zip(ext_ids, ws))
        env.update(zip(feed_vids, fs))
        for rec in kept:
            ins = [env[s[1]] if s[0] == "var" else s[1]
                   for s in rec.arg_spec]
            out = rec.fn(*ins, **rec.kwargs)
            if rec.multi:
                for oid, o in zip(rec.out_ids, out):
                    env[oid] = o
            else:
                env[rec.out_ids[0]] = out
        return tuple(env[vid] for vid in fetch_vids)

    return export_inference_artifact(fn, weight_vals, feed_specs, path_prefix)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns [inference_program, feed_target_names, fetch_targets]; run via
    executor.run(inference_program, feed={...}, fetch_list=fetch_targets)."""
    from ..inference.io import InferenceArtifact

    prog = InferenceProgram(InferenceArtifact.load(path_prefix))
    return [prog, prog.feed_names, prog.fetch_targets]


# ---------------------------------------------------------------------------
# CompiledProgram & strategies (the XLA pipeline makes these no-op shims)
# ---------------------------------------------------------------------------

class BuildStrategy:
    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """XLA compiles everything; this shim preserves the API
    (fluid/compiler.py CompiledProgram)."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self

    def __getattr__(self, item):
        return getattr(self._program, item)


# ---------------------------------------------------------------------------
# misc facade functions
# ---------------------------------------------------------------------------

def cpu_places(device_count=None):
    from ..framework import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework import CUDAPlace

    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def tpu_places(device_ids=None):
    from ..framework import TPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    # XLA owns placement; the reference used this to carve pipeline stages
    yield


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside a compiled program (reference: py_func_op.cc).

    TPU-native: ``jax.pure_callback`` — the XLA program calls back onto the
    host, runs ``func`` on numpy arrays, and resumes with its result, which
    must match ``out``'s shape/dtype (``out`` is a template Tensor or list,
    e.g. from ``paddle.zeros``). ``backward_func``, when given, follows the
    reference contract (py_func_op.cc): it is called with
    (inputs..., outputs..., out_grads...), minus any variables named in
    ``skip_vars_in_backward_input``, and returns the input grads."""
    import numpy as _np

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    multi = isinstance(out, (list, tuple))
    shapes = [jax.ShapeDtypeStruct(tuple(int(d) for d in o.shape), o._value.dtype)
              for o in outs]
    skip_names = {getattr(v, "name", None)
                  for v in (skip_vars_in_backward_input or [])}
    skip_names.discard(None)
    # positions of forward inputs/outputs passed to backward_func
    bwd_in_pos = [i for i, t in enumerate(xs)
                  if getattr(t, "name", None) not in skip_names]
    bwd_out_pos = [i for i, t in enumerate(outs)
                   if getattr(t, "name", None) not in skip_names]

    def host(*vals):
        res = func(*[_np.asarray(v) for v in vals])
        seq = res if isinstance(res, (list, tuple)) else [res]
        return tuple(_np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(seq, shapes))

    def fn(*vals):
        res = jax.pure_callback(host, tuple(shapes), *vals)
        return tuple(res) if multi else res[0]

    if backward_func is not None:
        fwd = jax.custom_vjp(fn)

        def fwd_rule(*vals):
            o = fn(*vals)
            o_seq = o if isinstance(o, tuple) else (o,)
            return o, (vals, o_seq)

        def bwd_rule(res_, gout):
            vals, o_seq = res_
            gseq = gout if isinstance(gout, tuple) else (gout,)
            in_shapes = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vals]

            def bhost(*args):
                res = backward_func(*[_np.asarray(a) for a in args])
                seq = res if isinstance(res, (list, tuple)) else [res]
                return tuple(_np.asarray(r, dtype=s.dtype).reshape(s.shape)
                             for r, s in zip(seq, in_shapes))

            bargs = ([vals[i] for i in bwd_in_pos] +
                     [o_seq[i] for i in bwd_out_pos] + list(gseq))
            return tuple(jax.pure_callback(bhost, tuple(in_shapes), *bargs))

        fwd.defvjp(fwd_rule, bwd_rule)
        fn = fwd

    res = autograd.call_op(fn, *xs, op_name="py_func")
    return res


def set_program_state(program, state):
    for t in program.externals.values():
        name = getattr(t, "name", None)
        if name and name in state:
            t.set_value(np.asarray(state[name]))


def save(program, model_path, protocol=4):
    """Save all persistable variables of a program (parity: static.save)."""
    import pickle

    state = {}
    for t in program.externals.values():
        name = getattr(t, "name", None)
        if name:
            state[name] = np.asarray(t.numpy())
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)


# ---------------------------------------------------------------------------
# static-namespace tail (reference: python/paddle/static/__init__.py __all__)
# ---------------------------------------------------------------------------

from ..framework.tensor import create_parameter  # noqa: F401,E402


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A persistable global variable with a constant value (reference:
    fluid/layers/tensor.py create_global_var)."""
    t = Tensor(jnp.full([int(s) for s in shape], value,
                        dtype=_convert_dtype(dtype)), _internal=True)
    t.stop_gradient = True
    t.persistable = persistable
    if name:
        t.name = name
    return t


def _convert_dtype(d):
    from ..framework.dtype import convert_dtype

    return convert_dtype(d)


def xpu_places(device_ids=None):
    return cpu_places()


def npu_places(device_ids=None):
    return cpu_places()


def mlu_places(device_ids=None):
    return cpu_places()


def accuracy(input, label, k=1, correct=None, total=None):
    """Static accuracy op (reference: fluid/layers/metric_op.py accuracy):
    top-k accuracy of predictions vs labels."""
    def fn(pred, lbl):
        kk = min(int(k), pred.shape[-1])
        topk = jnp.argsort(pred, axis=-1)[..., -kk:]
        hit = jnp.any(topk == lbl.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return autograd.call_op(fn, input, label, op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Static AUC op (reference: fluid/layers/metric_op.py auc): ROC AUC of
    positive-class scores via the rank statistic. Returns (auc_out,) like
    the reference's first output."""
    def fn(pred, lbl):
        score = pred[..., 1] if pred.ndim == 2 and pred.shape[-1] == 2 \
            else pred.reshape(-1)
        y = lbl.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(score)
        ranks = jnp.empty_like(order).at[order].set(
            jnp.arange(1, score.shape[0] + 1))
        n_pos = jnp.sum(y)
        n_neg = y.shape[0] - n_pos
        sum_pos_ranks = jnp.sum(ranks * y)
        return (sum_pos_ranks - n_pos * (n_pos + 1) / 2.0) / jnp.maximum(
            n_pos * n_neg, 1.0)

    return autograd.call_op(fn, input, label, op_name="auc")


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference: print_op.cc): prints the tensor when the
    op executes (host callback under jit) and passes the value through."""
    msg = message or ""
    state = {"count": 0}

    def host(v):
        if first_n < 0 or state["count"] < first_n:
            state["count"] += 1
            flat = np.asarray(v).reshape(-1)[:summarize]
            print(f"{msg} shape={tuple(np.asarray(v).shape)} "
                  f"dtype={np.asarray(v).dtype} values={flat}")
        return np.asarray(v)

    def fn(v):
        return jax.pure_callback(
            host, jax.ShapeDtypeStruct(v.shape, v.dtype), v)

    return autograd.call_op(fn, input, op_name="print")


class WeightNormParamAttr:
    """ParamAttr requesting weight normalization (reference:
    fluid/param_attr.py WeightNormParamAttr). Consumed by nn.utils
    weight_norm when layers build their parameters."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..framework.param_attr import ParamAttr as _PA

        self._attr = _PA(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         need_clip=need_clip)
        self.dim = dim

    def __getattr__(self, item):
        return getattr(self.__dict__["_attr"], item)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference: fluid/optimizer.py
    ExponentialMovingAverage): update() folds current params into shadow
    values; apply() swaps shadows in (context manager restores)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def update(self, parameters=None):
        params = parameters or self._default_params()
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in params:
            k = id(p)
            v = np.asarray(p.numpy(), np.float32)
            if k not in self._shadow:
                self._shadow[k] = (p, v.copy())
            else:
                _, s = self._shadow[k]
                self._shadow[k] = (p, d * s + (1 - d) * v)

    def _default_params(self):
        prog = default_main_program()
        return [t for t in prog.all_parameters() if t.trainable]

    def apply(self, executor=None, need_restore=True):
        """Context manager swapping shadow values in (reference usage:
        ``with ema.apply(exe):``). Entering backs originals up exactly
        once; exiting restores them unless need_restore=False."""
        class _Ctx:
            def __enter__(ctx):
                if not self._backup:  # guard double-enter
                    for k, (p, s) in self._shadow.items():
                        self._backup[k] = p._value
                        p._value = jnp.asarray(s, p._value.dtype)
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    self.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for k, v in self._backup.items():
            p = self._shadow[k][0]
            p._value = v
        self._backup.clear()


class ParallelExecutor:
    """API-compat shim (reference: parallel_executor.h:51). Multi-device
    data parallelism dissolved into GSPMD batch sharding — run() delegates
    to the serial Executor over the active mesh."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class IpuStrategy:
    """IPU backend strategy — present for API parity; the TPU build has no
    IPU support (reference gates this behind compiled-with-IPU)."""

    def __init__(self):
        raise RuntimeError("IPU support is not compiled into the TPU build "
                           "(is_compiled_with_ipu() is False)")


class IpuCompiledProgram:
    def __init__(self, *a, **kw):
        raise RuntimeError("IPU support is not compiled into the TPU build "
                           "(is_compiled_with_ipu() is False)")


def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError("IPU support is not compiled into the TPU build "
                       "(is_compiled_with_ipu() is False)")


# -- program/persistables serialization family ------------------------------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Serialize the inference slice of a program to bytes (reference:
    static/io.py serialize_program → ProgramDesc proto bytes; here the
    StableHLO artifact payload)."""
    import pickle

    program = program or default_main_program()
    return pickle.dumps({
        "kind": "paddle_tpu.program",
        "text": program.to_string(),
        "feeds": [getattr(v, "name", None) for v in _listify(feed_vars)],
        "fetches": [getattr(v, "name", None) for v in _listify(fetch_vars)],
    })


def deserialize_program(data):
    """Inverse of serialize_program: returns a metadata-level Program
    mirror (op-less; executable artifacts use load_inference_model)."""
    import pickle

    meta = pickle.loads(data)
    if not isinstance(meta, dict) or meta.get("kind") != "paddle_tpu.program":
        raise ValueError("not a serialized paddle_tpu program")
    p = Program()
    p._serialized_meta = meta
    return p


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """All persistable variables of the program as bytes (reference:
    static/io.py serialize_persistables)."""
    import pickle

    program = program or default_main_program()
    state = {}
    for t in program.externals.values():
        name = getattr(t, "name", None)
        if name and getattr(t, "persistable", False):
            state[name] = np.asarray(t.numpy())
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle

    set_program_state(program, pickle.loads(data))


def save_to_file(path, content):
    """Reference: static/io.py save_to_file."""
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune a program to the feed→fetch slice (reference: static/io.py
    normalize_program). The tape Program replays only ops reachable from
    the fetches, so a clone carrying the slice metadata suffices."""
    p = program.clone()
    p._normalized_io = ([getattr(v, "name", None) for v in _listify(feed_vars)],
                        [getattr(v, "name", None) for v in _listify(fetch_vars)])
    return p


def load_program_state(model_path, var_list=None):
    """Reference: static/io.py load_program_state — the saved state dict."""
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def _listify(v):
    return list(v) if isinstance(v, (list, tuple)) else [v]


__all__ += [
    "create_parameter", "create_global_var", "xpu_places", "npu_places",
    "mlu_places", "accuracy", "auc", "Print", "WeightNormParamAttr",
    "ExponentialMovingAverage", "ParallelExecutor", "IpuStrategy",
    "IpuCompiledProgram", "ipu_shard_guard", "serialize_program",
    "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "save_to_file", "load_from_file",
    "normalize_program", "load_program_state",
]


# ---------------------------------------------------------------------------
# TensorArray (reference: LoDTensorArray + write_to_array/read_from_array/
# array_length ops, fluid/layers/control_flow.py create_array/array_write/
# array_read; lod_tensor_to_array/array_to_lod_tensor)
# ---------------------------------------------------------------------------

class LoDTensorArray(list):
    """Dynamic list of tensors (the reference's vector<LoDTensor> variable
    type). Host-side container: under jit, loops that append per step
    should use lax.scan (see while_loop); this type serves the fluid API
    surface (beam search, RNN memories in static programs)."""


def create_array(dtype="float32", initialized_list=None):
    arr = LoDTensorArray()
    if initialized_list:
        arr.extend(initialized_list)
    return arr


def array_write(x, i, array=None):
    """Write x at index i, growing the array as needed."""
    idx = int(np.asarray(i.numpy() if hasattr(i, "numpy") else i))
    if array is None:
        array = LoDTensorArray()
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    idx = int(np.asarray(i.numpy() if hasattr(i, "numpy") else i))
    return array[idx]


def array_length(array):
    from ..framework.tensor import to_tensor

    return to_tensor(np.int64(len(array)))


def lod_tensor_to_array(x, table=None):
    """Split a ragged LoDTensor into per-sequence entries (reference:
    lod_tensor_to_array_op)."""
    from ..framework.lod import LoDTensor
    from ..framework.tensor import to_tensor

    if isinstance(x, LoDTensor):
        lens = x.innermost_lengths()
        data = x.numpy()
        arr = LoDTensorArray()
        off = 0
        for n in lens:
            arr.append(to_tensor(data[off:off + n]))
            off += n
        return arr
    return LoDTensorArray([x])


def array_to_lod_tensor(array, table=None):
    """Inverse of lod_tensor_to_array."""
    from ..framework.lod import LoDTensor

    rows = [np.asarray(t.numpy()) for t in array]
    return LoDTensor(np.concatenate(rows, axis=0),
                     [[r.shape[0] for r in rows]])


__all__ += ["LoDTensorArray", "create_array", "array_write", "array_read",
            "array_length", "lod_tensor_to_array", "array_to_lod_tensor"]

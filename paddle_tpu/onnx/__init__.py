"""paddle.onnx — ONNX export.

Parity: paddle.onnx.export (python/paddle/onnx/export.py → paddle2onnx).
This stack's portable interchange is StableHLO (jax.export) rather than
ONNX; `export` emits StableHLO bytes next to a manifest, and raises a clear
error if true ONNX output is requested without the (unavailable) converter.
"""
from __future__ import annotations

import json
import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, format="stablehlo",
           **configs):
    if format == "onnx":
        raise NotImplementedError(
            "paddle2onnx is not available in this environment; export with "
            "format='stablehlo' (the XLA-native interchange) instead")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..jit.functional import FunctionalModule

    specs = input_spec or []
    args = []
    for spec in specs:
        shape = tuple(1 if (d is None or (isinstance(d, int) and d < 0))
                      else int(d) for d in spec.shape)
        args.append(jnp.zeros(shape, dtype=spec.dtype))
    fm = FunctionalModule(layer)
    pvals = fm.param_values()
    bvals = fm.buffer_values()
    key = jax.random.key(0)

    def fwd(*ins):
        out, _ = fm.call(pvals, bvals, key, ins, training=False)
        return out

    exported = jax.export.export(jax.jit(fwd))(*args)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(blob)
    with open(path + ".manifest.json", "w") as f:
        json.dump({
            "format": "stablehlo",
            "inputs": [{"shape": list(s.shape), "dtype": str(s.dtype),
                        "name": s.name} for s in specs],
        }, f, indent=2)
    return path + ".stablehlo"

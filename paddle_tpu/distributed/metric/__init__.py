"""paddle.distributed.metric — the yaml-configured monitor registry.

Reference: python/paddle/distributed/metric/metrics.py (init_metric
reads a yaml of `monitors` and registers per-phase AUC calculators on a
C++ Metric object; print_metric/print_auc format the rolled-up values).
TPU-native: the calculators are host-side GlobalMetrics accumulators
(incubate/fleet/utils/fleet_util.py — same bucketed math as the
reference's metrics.cc), keyed by (name, phase) in a MetricRegistry that
plays the metric_ptr role. Masked/cmatch variants reduce over the
subset selected by the mask at update() time rather than by variable
plumbing (there is no Scope).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ...incubate.fleet.utils.fleet_util import FleetUtil, GlobalMetrics

__all__ = ["MetricRegistry", "init_metric", "print_metric", "print_auc"]


class MetricRegistry:
    """The `metric_ptr` analog: named monitors with a JOINING/UPDATING
    phase tag (reference phase 1/0)."""

    def __init__(self):
        self._metrics: Dict[str, Tuple[int, GlobalMetrics]] = {}

    _PLAIN_METHODS = ("AucCalculator", "MaskAucCalculator",
                      "MultiTaskAucCalculator")

    def init_metric(self, method: str, name: str, label: str, target: str,
                    phase: int = -1, bucket_size: int = 1000000, **kw):
        if method not in self._PLAIN_METHODS:
            # uid/cmatch-GROUPED calculators need per-group state the
            # registry does not keep; reducing them to plain AUC would be
            # silently different semantics than the yaml declares
            import warnings

            warnings.warn(
                f"metric method {method!r} registers as plain (masked) "
                f"AUC here — uid/cmatch grouping is not implemented; "
                f"the reported value is NOT the grouped metric",
                stacklevel=3)
        n_thresholds = max(1, min(int(bucket_size), 1 << 20)) - 1
        self._metrics[name] = (int(phase),
                               GlobalMetrics(num_thresholds=n_thresholds))
        return self._metrics[name][1]

    def get(self, name: str) -> GlobalMetrics:
        return self._metrics[name][1]

    def update(self, name: str, preds, labels, mask=None):
        """Feed one batch; a mask (the MaskAucCalculator variant) keeps
        only the selected instances."""
        p = np.asarray(preds).reshape(-1)
        y = np.asarray(labels).reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            p, y = p[m], y[m]
        self.get(name).update(p, y)

    def get_metric_name_list(self, stage_num: int = -1):
        return [n for n, (ph, _) in self._metrics.items()
                if stage_num in (-1, ph)]

    def get_metric_msg(self, name: str):
        m = FleetUtil().get_global_metrics(self.get(name))
        return [m["auc"], m["bucket_error"], m["mae"], m["rmse"],
                m["actual_ctr"], m["predicted_ctr"], m["copc"],
                float(m["total_ins_num"])]

    def reset(self, name: Optional[str] = None):
        for n, (_, gm) in self._metrics.items():
            if name in (None, n):
                gm.reset()


def init_metric(metric_ptr: MetricRegistry, metric_yaml_path: str,
                cmatch_rank_var="", mask_var="", uid_var="", phase=-1,
                cmatch_rank_group="", ignore_rank=False,
                bucket_size=1000000):
    """Register every monitor in the yaml (reference metrics.py:26)."""
    import yaml

    with open(metric_yaml_path) as f:
        content = yaml.safe_load(f)
    for runner in content.get("monitors") or []:
        if "phase" in runner:
            ph = 1 if runner["phase"] == "JOINING" else 0
        else:
            ph = int(phase)  # the function arg supplies it (reference)
        metric_ptr.init_metric(
            runner.get("method", "AucCalculator"), runner["name"],
            runner.get("label", ""), runner.get("target", ""),
            phase=ph, bucket_size=bucket_size)


def print_metric(metric_ptr: MetricRegistry, name: str) -> str:
    m = metric_ptr.get_metric_msg(name)
    msg = ("%s: AUC=%.6f BUCKET_ERROR=%.6f MAE=%.6f RMSE=%.6f "
           "Actual CTR=%.6f Predicted CTR=%.6f COPC=%.6f INS Count=%.0f"
           % (name, *m))
    FleetUtil().rank0_print(msg)
    return msg


def print_auc(metric_ptr: MetricRegistry, is_day: bool,
              phase: str = "all") -> list:
    """Print every monitor of the stage (reference metrics.py:116)."""
    stage_num = -1 if is_day else (1 if phase == "join" else 0)
    return [print_metric(metric_ptr, n)
            for n in metric_ptr.get_metric_name_list(stage_num)]

"""Collective communication API.

Reference: python/paddle/distributed/collective.py (all_reduce:427,
new_group:209, broadcast/all_gather/reduce_scatter/alltoall/send/recv) backed
by the c_* op family (operators/collective/, 132 files) on NCCL rings.

TPU-native: a Group is a view onto mesh axes. Inside a shard_map region the
functions lower to jax.lax collectives (psum/all_gather/ppermute/all_to_all →
XLA AllReduce/AllGather/CollectivePermute/AllToAll over ICI). Outside, on a
sharded Tensor, they execute a tiny pjit'd program over the mesh. With
world == 1 they degrade to copies, matching the reference's single-card
behavior. Stream-ordering ops (c_sync_calc_stream etc.) have no analog — XLA
schedules — and `wait` is a device sync.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.autograd import call_op
from ..framework.tensor import Tensor
from ..observability import get_event_log, rpc_profiler_enabled
from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import get_registry as _get_registry
from . import mesh as mesh_mod

# per-kind issue counters (ISSUE 3 sweep): every collective that enters this
# module is counted, trace or eager, so step-time reports can cross-check the
# grad_comm plan against what actually ran
_m_collectives = _get_registry().counter(
    "collectives_total", help="collectives issued through this module",
    labels=("op",))

# always-on flight recorder (ISSUE 6): importing the collective layer arms
# the ring, so by the time anything can hang there is history to dump
_flightrec = get_flight_recorder()


def _nbytes(val):
    try:
        return int(val.size) * np.dtype(val.dtype).itemsize
    except (TypeError, ValueError, AttributeError):
        return None


def _record_collective(kind, val=None):
    _m_collectives.labels(op=kind).inc()
    _flightrec.note("collective", kind, bytes=_nbytes(val))
    if rpc_profiler_enabled():
        # FLAGS_enable_rpc_profiler (reference: per-RPC spans in the fluid
        # PS path) — reinterpreted as per-collective event records
        get_event_log().debug("collective", op=kind, bytes=_nbytes(val))


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group = a set of mesh axes (reference: collective.py:79
    Group over an NCCL ring). `timeout` (seconds) bounds every eager
    collective issued on the group (robustness/distributed_ft); None falls
    back to FLAGS_collective_timeout_s, 0 disables."""

    def __init__(self, gid: int, axes, ranks: Optional[List[int]] = None,
                 nranks=None, timeout=None):
        self.id = gid
        self.axes = tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)
        self.ranks = ranks or []
        self._nranks = nranks
        self.timeout = _timeout_seconds(timeout)

    @property
    def nranks(self):
        if self._nranks is not None:
            return self._nranks
        n = 1
        for ax in self.axes:
            n *= mesh_mod.axis_size(ax)
        return n

    @property
    def world_size(self):
        return self.nranks

    @property
    def name(self):
        return f"group_{self.id}"

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if self.ranks else rank

    def __repr__(self):
        timeout = f", timeout={self.timeout}s" if self.timeout else ""
        return (f"Group(id={self.id}, axes={self.axes}, "
                f"nranks={self.nranks}{timeout})")


def _timeout_seconds(timeout):
    """Normalize a group timeout: seconds (int/float) or a timedelta (the
    reference new_group(timeout=) signature). None = inherit the
    FLAGS_collective_timeout_s default at call time."""
    if timeout is None:
        return None
    if hasattr(timeout, "total_seconds"):
        timeout = timeout.total_seconds()
    return float(timeout)


def _guarded(kind, group, thunk, payload=None):
    """Run an eager collective body through the fault-tolerance layer
    (robustness/distributed_ft.execute_collective): per-group timeout with
    bounded retries, transient-failure backoff, chaos injection. Thunks
    compute and RETURN the new value without mutating their input tensor —
    a timed-out attempt is abandoned on its worker thread and must not race
    the retry. In-trace calls never come here (XLA owns their schedule)."""
    from ..robustness.distributed_ft import execute_collective

    return execute_collective(kind, group, thunk, payload=payload)


_groups: Dict[int, Group] = {}
_next_gid = [1]


def _world_group() -> Group:
    # rebuilt per call: the mesh may be (re)configured after the first
    # collective, and caching would freeze stale axes
    m = mesh_mod.get_mesh()
    axes = m.axis_names if m is not None else (mesh_mod.AXIS_DATA,)
    return Group(0, axes)


def new_group(ranks=None, backend=None, axes=None, timeout=None) -> Group:
    """reference: collective.py:209. On TPU a group is identified by mesh axes;
    `axes` is the native way to create one. `ranks` is accepted for API compat
    (stored for bookkeeping; the mesh topology determines the communicator).

    `timeout` (seconds or timedelta, reference signature) bounds every eager
    collective on the group; when omitted the group inherits the
    FLAGS_collective_timeout_s default (0 = unbounded)."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    if axes is None:
        axes = mesh_mod.get_mesh().axis_names if mesh_mod.get_mesh() else (mesh_mod.AXIS_DATA,)
    if timeout is None:
        from ..framework.flags import flag

        timeout = float(flag("FLAGS_collective_timeout_s", 0.0) or 0.0) or None
    g = Group(gid, axes, ranks=list(ranks) if ranks else None,
              nranks=len(ranks) if ranks else None, timeout=timeout)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    return _groups.get(gid) or _world_group()


def _axes(group: Optional[Group]):
    g = group or _world_group()
    return g.axes


def _in_trace(val) -> bool:
    return isinstance(val, jax.core.Tracer)


def _psum_like(val, axes, op):
    if op == ReduceOp.SUM:
        return jax.lax.psum(val, axes)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(val, axes)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(val, axes)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(val, axes)
    if op == ReduceOp.PROD:
        return jnp.exp(jax.lax.psum(jnp.log(val), axes))
    raise ValueError(f"unsupported ReduceOp {op}")


def in_trace_psum(val, axis, op=ReduceOp.SUM):
    """Sanctioned raw in-trace collective for manual-SPMD model math.

    Model code inside a ``shard_map`` region (gpt's tensor/sequence-
    parallel forward, custom parallel layers) needs bare ``lax.psum``-
    shaped reductions on raw jnp values — no Tensor wrapper, no eager
    path, differentiable (psum has a transpose rule; this must stay on
    the autodiff path). Routing those through this helper instead of raw
    ``jax.lax`` keeps the collective ACCOUNTED — per-op counters and a
    flight-recorder note at trace time — and keeps rule X001 ("raw lax
    collectives only inside distributed/") enforceable at zero baseline.

    ``axis`` is a mesh axis name or tuple of names; the value must be a
    traced value inside a manual-SPMD region (eager callers want
    ``all_reduce`` on a Tensor, which adds the timeout/retry guards)."""
    _record_collective("in_trace_psum", val)
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    return _psum_like(val, axes, op)


def in_trace_all_gather(val, axis, gather_axis=0, tiled=True):
    """``in_trace_psum``'s gather sibling for manual-SPMD model math.

    The ZeRO-3 x pipeline stage body (models/gpt.py) re-materializes its
    stage's at-rest weight shards with this; all_gather's transpose is
    psum_scatter, so the gather stays ON the autodiff path and its VJP
    both sums the batch-shard grad contributions and re-shards the
    result — the stage-3 gradient direction for free."""
    _record_collective("in_trace_all_gather", val)
    return jax.lax.all_gather(val, axis, axis=gather_axis, tiled=tiled)


def in_trace_pmax(val, axis):
    """``in_trace_psum``'s MAX sibling for manual-SPMD model math.

    pmax has no VJP — callers keep it off the gradient path (gpt wraps
    the operand in stop_gradient; the max-shift cancels out of the
    cross-entropy gradient exactly)."""
    _record_collective("in_trace_pmax", val)
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    return _psum_like(val, axes, ReduceOp.MAX)


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """reference: collective.py:427 → c_allreduce_sum op → XLA AllReduce."""
    _record_collective("all_reduce", tensor._value)
    axes = _axes(group)
    val = tensor._value
    if _in_trace(val):
        # inside shard_map: lower directly
        new = call_op(lambda v: _psum_like(v, axes, op), tensor, op_name="all_reduce")
        tensor._replace_from(new)
        return tensor
    n = _group_size(axes, group)

    def _eager():
        # re-read the value: chaos bit-flips corrupt the input in place
        v = tensor._value
        if n <= 1:
            return v
        # eager on a sharded value: pjit'd psum via shard_map over the mesh
        from jax.sharding import PartitionSpec as P

        m = mesh_mod.default_mesh()
        f = mesh_mod.compat_shard_map(
            lambda x: _psum_like(x, axes, op),
            m, P(*axes), P(*axes),
        )
        return f(v)

    tensor._value = _guarded("all_reduce", group, _eager, payload=tensor)
    return tensor


def _group_size(axes, group):
    if group is not None and group._nranks is not None:
        return group._nranks
    n = 1
    for ax in axes:
        n *= mesh_mod.axis_size(ax)
    return n


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """reference: c_allgather. In-trace: lax.all_gather; eager: device fan-in."""
    _record_collective("all_gather", tensor._value)
    axes = _axes(group)
    val = tensor._value
    if _in_trace(val):
        gathered = call_op(
            lambda v: jax.lax.all_gather(v, axes[0], tiled=False), tensor,
            op_name="all_gather",
        )
        if tensor_list is not None:
            n = _group_size(axes, group)
            for i in range(n):
                tensor_list.append(gathered[i])
            return tensor_list
        return gathered
    n = _group_size(axes, group)
    cloned = _guarded("all_gather", group, tensor.clone, payload=tensor)
    if tensor_list is not None:
        tensor_list.append(cloned)
        for _ in range(n - 1):
            tensor_list.append(tensor.clone())
        return tensor_list
    return cloned


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # SPMD: reduce == all_reduce (every shard holds the result)
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """reference: collective.py reduce_scatter → c_reducescatter op.

    Tensor form: each rank keeps the reduction of its own 1/nranks chunk of
    dim 0 — the inverse pairing of all_gather (all_gather(reduce_scatter(x))
    == all_reduce(x)), and the half of ring all-reduce the ZeRO stage-2 grad
    path wants. List form (paddle's reduce_scatter(output, input_list)):
    rank r's output is the reduction over ranks of input_list[r]; the result
    lands in `tensor` when given.

    In-trace: lax.psum_scatter (tiled for the chunked tensor form); AVG
    divides by the group size. Eager with world == 1 it degrades to the
    reduction of the local inputs, matching the reference's single-card
    behavior; on a sharded value it runs a pjit'd psum_scatter over the mesh
    like all_reduce does.
    """
    _record_collective(
        "reduce_scatter",
        tensor._value if tensor is not None else tensor_list[0]._value)
    axes = _axes(group)
    n = _group_size(axes, group)

    def _avg(v):
        return v / n if op == ReduceOp.AVG else v

    lax_op = ReduceOp.SUM if op == ReduceOp.AVG else op
    if lax_op != ReduceOp.SUM:
        raise ValueError("reduce_scatter supports SUM/AVG only")

    if tensor_list is not None:
        # list form: stack per-destination inputs on a leading axis
        vals = [t._value for t in tensor_list]
        if _in_trace(vals[0]):
            stacked = jnp.stack(vals)
            out = _avg(jax.lax.psum_scatter(stacked, axes[0], tiled=False))
            new = Tensor(out, _internal=True)
        else:
            # eager single-process world: reduce over the (replicated) list
            def _eager_list():
                acc = tensor_list[0]._value
                for t in tensor_list[1:]:
                    acc = acc + t._value
                return _avg(acc) if n > 1 else acc

            new = Tensor(_guarded("reduce_scatter", group, _eager_list,
                                  payload=tensor_list[0]), _internal=True)
        if tensor is not None:
            tensor._value = new._value.astype(tensor._value.dtype)
            return tensor
        return new

    val = tensor._value
    if _in_trace(val):
        new = call_op(
            lambda v: _avg(jax.lax.psum_scatter(
                v, axes if len(axes) > 1 else axes[0],
                scatter_dimension=0, tiled=True)),
            tensor, op_name="reduce_scatter")
        return new
    def _eager():
        v = tensor._value
        if n <= 1:
            return v
        # eager on a sharded value: pjit'd psum_scatter over the mesh
        from jax.sharding import PartitionSpec as P

        m = mesh_mod.default_mesh()
        f = mesh_mod.compat_shard_map(
            lambda x: _avg(jax.lax.psum_scatter(
                x, axes if len(axes) > 1 else axes[0],
                scatter_dimension=0, tiled=True)),
            m, P(*axes), P(*axes),
        )
        return f(v)

    return Tensor(_guarded("reduce_scatter", group, _eager, payload=tensor),
                  _internal=True)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """reference: c_broadcast. SPMD: values are replicated by construction;
    in-trace this selects src's shard via ppermute-free psum of a masked value."""
    _record_collective("broadcast", tensor._value)
    axes = _axes(group)
    val = tensor._value
    if _in_trace(val):
        def fn(v):
            idx = jax.lax.axis_index(axes[0])
            masked = jnp.where(idx == src, v, jnp.zeros_like(v))
            return jax.lax.psum(masked, axes[0])

        new = call_op(fn, tensor, op_name="broadcast")
        tensor._replace_from(new)
        return tensor
    # eager: replication is the SPMD invariant — a no-op wire-wise, but it
    # still passes through the guard so chaos/timeout policies apply
    tensor._value = _guarded("broadcast", group, lambda: tensor._value,
                             payload=tensor)
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _in_trace(tensor._value):
        raise NotImplementedError("in-trace scatter: index the sharded input instead")
    if tensor_list:
        tensor.set_value(tensor_list[get_rank_in(group)])
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """reference: alltoall op (MoE routing). In-trace: lax.all_to_all."""
    _record_collective(
        "alltoall",
        in_tensor_list._value if isinstance(in_tensor_list, Tensor)
        else in_tensor_list[0]._value)
    axes = _axes(group)
    if isinstance(in_tensor_list, Tensor):
        t = in_tensor_list
        if _in_trace(t._value):
            return call_op(
                lambda v: jax.lax.all_to_all(v, axes[0], split_axis=0, concat_axis=0,
                                             tiled=True),
                t, op_name="alltoall",
            )
        return _guarded("alltoall", group, t.clone, payload=t)
    # list form: single process == identity permutation
    outs = [t.clone() for t in in_tensor_list]
    if out_tensor_list is not None:
        out_tensor_list.extend(outs)
        return out_tensor_list
    return outs


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send (send_v2). In-trace, use ppermute via sendrecv(); eager
    single-process p2p is a no-op."""
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def sendrecv(value, perm, axis):
    """Native p2p: collective_permute over `axis` with (src, dst) pairs —
    the building block the pipeline scheduler uses."""
    return jax.lax.ppermute(value, axis, perm)


def barrier(group=None):
    (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    # XLA schedules; just synchronize the host on the value
    v = tensor._value
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()
    return tensor


def get_rank_in(group=None):
    from .env import get_rank

    return get_rank()


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True, weight_attr=None,
          bias_attr=None, name=None):
    """paddle.distributed.split (collective.py:1277) — auto-sharded
    linear/embedding. TPU-native: use fleet.meta_parallel
    {ColumnParallelLinear,RowParallelLinear,VocabParallelEmbedding}; this
    facade constructs the matching layer."""
    from .fleet.meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    )

    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unknown split operation {operation}")

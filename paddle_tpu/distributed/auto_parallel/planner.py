"""Mesh planner: search parallelism plans with the REAL TPU compiler as
the cost model.

Reference: python/paddle/distributed/auto_parallel/planner.py:829 (Planner
+ MCMC searcher over process-mesh topologies and per-op dims mappings) and
cost_model.py (a hand-written simulator of op runtimes and comm latencies
that scores each candidate distributed program).

TPU-native inversion: there is nothing to simulate — XLA-TPU will compile
the actual train step for any candidate mesh ahead-of-time (via
jax.experimental.topologies, no TPU hardware or execution needed) and its
cost model reports `optimal_seconds` and per-device memory for the REAL
fused/sharded program. So the planner is: enumerate mesh factorizations,
AOT-compile each candidate, rank by compiler-estimated step time subject
to the HBM budget. The "cost model" can never drift from the executor,
because it IS the compiler that produces the executable.

    def builder(shape_map, activate_mesh):
        model = ...                      # build with NO mesh active
        optim = ...
        activate_mesh()                  # then switch on the candidate mesh
        return TrainStep(...), (inputs,), (labels,)

    plans = plan(builder, n_devices=8,
                 axes=("data", "sharding", "model"))
    best = plans[0]          # .shape_map, .est_seconds, .peak_hbm_bytes

Builders see the candidate only through `shape_map` and must create real
arrays BEFORE calling `activate_mesh()`: topology devices are described,
not addressable, so arrays cannot live on them — only the abstract
lowering may see the mesh (same rule as tools/hybrid_aot_tpu.py).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["MeshPlan", "enumerate_factorizations", "plan", "rank_key"]

# v5e slices by chip count, smallest viable layout per size
_V5E_TOPOLOGIES = {8: "v5e:2x4", 16: "v5e:4x4", 32: "v5e:4x8",
                   64: "v5e:8x8"}


@dataclass
class MeshPlan:
    """One ranked candidate: mesh shape + the TPU compiler's verdict."""
    shape_map: Dict[str, int]
    est_seconds: Optional[float] = None       # step-time estimate (ranking)
    est_signal: Optional[str] = None          # "compiler" | "roofline"
    peak_hbm_bytes: Optional[int] = None
    compile_seconds: float = 0.0
    fits: bool = True                         # under the hbm budget
    error: Optional[str] = None               # compile failure (plan culled)
    flops: Optional[float] = None

    def __repr__(self):
        if self.error:
            return f"MeshPlan({self.shape_map}, error={self.error[:60]!r})"
        os_ = (f"{self.est_seconds*1e3:.2f}ms({self.est_signal})"
               if self.est_seconds is not None else "?")
        mem = (f"{self.peak_hbm_bytes/2**30:.2f}GiB"
               if self.peak_hbm_bytes is not None else "?")
        return (f"MeshPlan({self.shape_map}, est_step={os_}, "
                f"hbm/dev={mem}, fits={self.fits})")


def rank_key(p: MeshPlan):
    """Sort key for candidate plans. A roofline estimate is a documented
    LOWER bound that ignores collective/ICI time, so it systematically
    flatters communication-heavy shardings; in a mixed comparison every
    compiler-signal plan ranks ahead of every roofline-signal one."""
    signal_rank = 0 if p.est_signal == "compiler" else 1
    if p.error:
        return (2, 1, 0.0)
    if not p.fits:
        return (1, signal_rank, p.est_seconds or float("inf"))
    return (0, signal_rank, p.est_seconds
            if p.est_seconds is not None else float("inf"))


def enumerate_factorizations(n_devices: int, axes: Sequence[str],
                             caps: Optional[Dict[str, int]] = None,
                             ) -> List[Dict[str, int]]:
    """All assignments of n_devices' prime factors onto `axes` (degree-1
    axes dropped), honoring per-axis caps — the reference PlanFilter's
    divisibility pruning (planner.py:45) in factorization form."""
    caps = caps or {}

    def primes(n):
        out, p = [], 2
        while n > 1:
            while n % p == 0:
                out.append(p)
                n //= p
            p += 1 if p == 2 else 2
        return out

    plans = [{}]
    for p in primes(n_devices):
        nxt = []
        for partial in plans:
            for ax in axes:
                cand = dict(partial)
                cand[ax] = cand.get(ax, 1) * p
                if cand[ax] <= caps.get(ax, 1 << 30):
                    nxt.append(cand)
        # dedupe (order of equal primes doesn't matter)
        seen, plans = set(), []
        for c in nxt:
            key = tuple(sorted(c.items()))
            if key not in seen:
                seen.add(key)
                plans.append(c)
    if not plans:
        raise ValueError(
            f"caps {caps} leave no way to place {n_devices} devices on "
            f"axes {tuple(axes)} — raise a cap or add an axis")
    return [{a: d for a, d in c.items() if d > 1} or {axes[0]: 1}
            for c in plans]


def _topology_mesh(n_devices: int, shape_map: Dict[str, int]):
    from ...jit.aot import topology_mesh

    name = _V5E_TOPOLOGIES.get(n_devices)
    if name is None:
        raise ValueError(
            f"no described v5e topology with {n_devices} chips; "
            f"have {sorted(_V5E_TOPOLOGIES)}")
    return topology_mesh(name, shape_map)


def plan(step_builder: Callable, n_devices: int,
         axes: Sequence[str] = ("data", "sharding", "model"),
         caps: Optional[Dict[str, int]] = None,
         hbm_budget_bytes: Optional[int] = 16 * 2**30,
         max_candidates: Optional[int] = None,
         verbose: bool = True) -> List[MeshPlan]:
    """Rank mesh factorizations for `step_builder` by TPU-compiler cost.

    step_builder(shape_map, activate_mesh) -> (step, inputs, labels);
    it must call activate_mesh() AFTER creating all real arrays.
    Returns MeshPlans sorted best-first: feasible (fits budget, compiled)
    plans by optimal_seconds, then infeasible, then failed.
    """
    from .. import mesh as mesh_mod

    cands = enumerate_factorizations(n_devices, axes, caps)
    if max_candidates is not None:
        cands = cands[:max_candidates]
    plans: List[MeshPlan] = []
    prev = mesh_mod.get_mesh()
    try:
        for shape_map in cands:
            mp = MeshPlan(dict(shape_map))
            t0 = time.time()
            try:
                mesh_mod.set_mesh(None)

                def activate_mesh(sm=shape_map):
                    mesh_mod.set_mesh(_topology_mesh(n_devices, sm))

                step, inputs, labels = step_builder(dict(shape_map),
                                                    activate_mesh)
                from ...jit.aot import aot_compile_step, estimate_step_seconds

                cost = aot_compile_step(step, inputs, labels,
                                        want_cost=True)
                mp.compile_seconds = round(time.time() - t0, 1)
                est = estimate_step_seconds(cost)
                if est is not None:
                    mp.est_seconds = est["seconds"]
                    mp.est_signal = est["signal"]
                mp.peak_hbm_bytes = cost.get("peak_hbm_bytes")
                mp.flops = cost.get("flops")
                if (hbm_budget_bytes is not None
                        and mp.peak_hbm_bytes is not None):
                    mp.fits = mp.peak_hbm_bytes <= hbm_budget_bytes
            except Exception as e:  # a candidate failing to compile is
                mp.error = f"{type(e).__name__}: {e}"   # data, not fatal
                mp.compile_seconds = round(time.time() - t0, 1)
            if verbose:
                print(f"  planner: {mp}")
            plans.append(mp)
    finally:
        mesh_mod.set_mesh(prev)

    plans.sort(key=rank_key)
    return plans

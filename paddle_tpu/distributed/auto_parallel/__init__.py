"""paddle.distributed.auto_parallel — the annotation API over GSPMD.

Reference: python/paddle/distributed/auto_parallel/ (12.5k LoC:
ProcessMesh + shard_tensor annotations, then Completer/Partitioner passes
that propagate distributed attributes and rewrite the program,
completion.py:326, partitioner.py:34).

TPU-native: the ENGINE is XLA GSPMD — annotate shardings and the compiler
does completion/partitioning/collective-insertion. This package supplies the
user-facing surface: ProcessMesh, the Shard/Replicate/Partial placements,
shard_tensor / shard_layer / reshard. The reference's pass pipeline has no
analog to port — with_sharding_constraint + jit IS the completer.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_mod
from ...framework.tensor import Tensor

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "shard_layer", "reshard", "get_mesh", "set_mesh",
           "dtensor_from_fn", "planner"]

from . import planner  # noqa: E402  (compiler-as-cost-model mesh search)


class ProcessMesh:
    """reference process_mesh.py ProcessMesh(mesh, dim_names): an N-D array
    of ranks with named dims. Backed by a jax.sharding.Mesh."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        if len(self.dim_names) != arr.ndim:
            raise ValueError("dim_names must match mesh rank")
        devices = np.asarray(jax.devices())
        if devices.size < arr.size:
            raise ValueError(
                f"ProcessMesh wants {arr.size} devices, have {devices.size}")
        self._jax_mesh = Mesh(
            devices[np.asarray(self.process_ids)].reshape(arr.shape),
            tuple(self.dim_names))

    @property
    def ndim(self):
        return len(self.shape)

    def get_jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self.shape == other.shape
                and self.process_ids == other.process_ids
                and self.dim_names == other.dim_names)

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self.dim_names})")


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard(d): tensor dim d splits across this mesh dim."""

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)


class Partial(Placement):
    """Pending-reduction placement. GSPMD materializes partials internally;
    explicitly placing one means 'reduce on next use' — we reduce eagerly."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def _placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                        ndim: int) -> P:
    """placements[i] describes how the tensor lays out along MESH dim i
    (reference dist_tensor semantics) → a PartitionSpec over tensor dims."""
    entries: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis = mesh.dim_names[mesh_dim]
            cur = entries[pl.dim]
            if cur is None:
                entries[pl.dim] = axis
            elif isinstance(cur, tuple):
                entries[pl.dim] = cur + (axis,)
            else:
                entries[pl.dim] = (cur, axis)
        elif isinstance(pl, Partial):
            raise ValueError(
                "Partial placements cannot be assigned via shard_tensor; "
                "they arise from computation (GSPMD reduces them at use)")
    return P(*entries)


def shard_tensor(tensor, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, stop_gradient=None):
    """Place a Tensor onto `mesh` per `placements` (reference api.py
    shard_tensor). Under jit tracing this lowers to a sharding constraint;
    eagerly it device_puts the value with the NamedSharding."""
    if not isinstance(tensor, Tensor):
        tensor = Tensor(tensor, dtype=dtype)
    jm = mesh.get_jax_mesh()
    spec = _placements_to_spec(placements, mesh, tensor._value.ndim)
    if isinstance(tensor._value, jax.core.Tracer):
        out = Tensor(jax.lax.with_sharding_constraint(
            tensor._value, NamedSharding(jm, spec)), _internal=True)
    else:
        out = Tensor(jax.device_put(tensor._value, NamedSharding(jm, spec)),
                     _internal=True)
    out.stop_gradient = (tensor.stop_gradient if stop_gradient is None
                         else stop_gradient)
    out.dist_spec = spec
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def reshard(tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Re-layout a dist tensor (reference api.py reshard). XLA emits the
    minimal collective (all-gather / all-to-all / slice) for the move."""
    return shard_tensor(tensor, mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Apply `shard_fn(name, layer, mesh)` to every sublayer (reference
    api.py shard_layer); default replicates parameters onto the mesh."""
    def default_fn(name, sub, mesh):
        for pname, param in sub.named_parameters(include_sublayers=False):
            n = param._value.ndim
            placed = shard_tensor(param, mesh,
                                  [Replicate()] * len(mesh.shape))
            param._value = placed._value
            param.dist_spec = placed.dist_spec

    fn = shard_fn or default_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    return layer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    """Build then place (reference api.py dtensor_from_fn — e.g.
    dtensor_from_fn(paddle.ones, mesh, [Shard(0)], shape=[...]))."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def get_mesh() -> Optional[ProcessMesh]:
    m = mesh_mod.get_mesh()
    if m is None:
        return None
    pm = ProcessMesh.__new__(ProcessMesh)
    pm.shape = list(m.devices.shape)
    pm.dim_names = list(m.axis_names)
    pm.process_ids = list(range(m.devices.size))
    pm._jax_mesh = m
    return pm


def set_mesh(mesh: ProcessMesh):
    mesh_mod.set_mesh(mesh.get_jax_mesh())
    return mesh

"""Fleet executor — the actor-style control plane for distributed inference.

Reference: paddle/fluid/distributed/fleet_executor/ (~8k LoC C++):
FleetExecutor builds a task graph of TaskNodes, a Carrier per rank hosts
Interceptors (actors) that exchange messages over a MessageBus, and
micro-batches flow source → compute stages → sink with credit-based flow
control (compute_interceptor.cc UpSteam/DownStream buffs).

TPU-native framing: the DATA plane of multi-stage inference is the SPMD
pipeline (distributed/pipeline.py) — XLA moves activations over ICI. What
the fleet executor keeps is the HOST control plane: asynchronous stage
orchestration for host-resident steps (pre/post-processing, PS lookups,
detokenization) around compiled programs. Actors are threads with
queues; the MessageBus routes by task id, and when the destination
carrier lives in another process the message rides the same
length-prefixed TLV socket framing as distributed/ps (reference
message_bus.cc:180 Send → brpc InterceptorMessageService — here a
persistent TCP connection per peer rank). Interceptors flow-control with
credit frames (compute_interceptor.cc UpStream/DownStream buffs): a
stage may hold at most `max_run_times` un-acked micro-batches per
downstream edge, credits returning as CREDIT messages over the same bus.
"""
from __future__ import annotations

import collections
import itertools
import queue as queue_mod
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["TaskNode", "Interceptor", "ComputeInterceptor", "Carrier",
           "MessageBus", "FleetExecutor", "ServiceInterceptor",
           "BusRpcClient", "DistModel", "DistModelConfig"]

_STOP = "__stop__"
DATA = "data"
DONE = "done"
CREDIT = "credit"
REQUEST = "request"
REPLY = "reply"

_seq = itertools.count()  # inbox FIFO tiebreaker


@dataclass
class Message:
    src_id: int
    dst_id: int
    type: str
    payload: Any = None
    scope_idx: int = 0


@dataclass
class TaskNode:
    """fleet_executor/task_node.h: one stage of the task graph.

    max_run_times is the stage's micro-batch concurrency credit (how many
    un-acked micro-batches each upstream may have in flight toward it —
    reference compute_interceptor.cc down_buffs). The default of 2 keeps
    adjacent stages double-buffered; 1 enforces strict lockstep."""

    task_id: int
    rank: int = 0
    max_run_times: int = 2  # micro-batch concurrency credit
    fn: Optional[Callable] = None  # the stage computation (compiled program)
    downstream: List[int] = field(default_factory=list)
    upstream: List[int] = field(default_factory=list)
    role: str = "compute"  # source | compute | sink


class _BusHandler(socketserver.BaseRequestHandler):
    """One persistent inbound connection from a peer bus: a stream of
    TLV-framed message dicts, each delivered to the local inbox."""

    def handle(self):
        from .ps import _recv_msg

        while True:
            frame = _recv_msg(self.request)
            if frame is None:
                return
            self.server.bus._deliver_local(Message(
                int(frame["src"]), int(frame["dst"]), frame["type"],
                frame.get("payload"), int(frame.get("scope", 0))))


class _BusServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MessageBus:
    """message_bus.cc + interceptor_message_service.cc analog.

    Local routing is task-id → inbox. Cross-host routing follows the
    reference's shape (message_bus.cc:180): a task→rank map decides
    whether Send() is an in-process enqueue or a network hop; remote
    hops use one persistent TCP connection per peer rank carrying the
    distributed/ps TLV framing (numpy payloads cross intact, closed
    schema — no pickle).

        bus = MessageBus(rank=0, task_ranks={0: 0, 1: 1})
        ep = bus.listen()                 # "host:port" for peers
        bus.connect(1, peer_endpoint)     # rank 1's listen() result
    """

    def __init__(self, rank: int = 0,
                 task_ranks: Optional[Dict[int, int]] = None,
                 endpoints: Optional[Dict[int, str]] = None):
        self._inboxes: Dict[int, "queue_mod.Queue"] = {}
        self._lock = threading.Lock()
        self.rank = int(rank)
        self._task_ranks = dict(task_ranks or {})
        self._peer_eps: Dict[int, str] = dict(endpoints or {})
        self._peer_socks: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._server: Optional[_BusServer] = None

    # ---- lifecycle ----------------------------------------------------
    def listen(self, host: str = "127.0.0.1", port: int = 0,
               advertise_host: Optional[str] = None) -> str:
        """Start accepting peer connections; returns this bus's endpoint.
        When binding a wildcard address pass `advertise_host` (or the
        machine's hostname is used) so peers get a reachable address, not
        0.0.0.0."""
        if self._server is None:
            self._server = _BusServer((host, port), _BusHandler)
            self._server.bus = self
            threading.Thread(target=self._server.serve_forever,
                             daemon=True).start()
        h, p = self._server.server_address[:2]
        if advertise_host:
            h = advertise_host
        elif h in ("0.0.0.0", "::"):
            h = socket.gethostname()
        return f"{h}:{p}"

    def connect(self, rank: int, endpoint: str):
        """Register (lazily dialed) the endpoint of a peer bus."""
        self._peer_eps[int(rank)] = endpoint

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        for s in self._peer_socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._peer_socks.clear()

    # ---- routing ------------------------------------------------------
    def register(self, task_id: int) -> "queue_mod.PriorityQueue":
        with self._lock:
            q = queue_mod.PriorityQueue()
            self._inboxes[task_id] = q
            return q

    def _deliver_local(self, msg: Message):
        with self._lock:
            box = self._inboxes.get(msg.dst_id)
        if box is None:
            raise KeyError(f"no interceptor registered for task "
                           f"{msg.dst_id}")
        # CREDIT frames jump ahead of queued DATA (they commute with data
        # processing; behind a slow stage's sleeps they would starve the
        # upstream). DATA/DONE keep FIFO order so DONE can never overtake
        # the data it follows.
        box.put((0 if msg.type == CREDIT else 1, next(_seq), msg))

    def _peer(self, rank: int) -> socket.socket:
        s = self._peer_socks.get(rank)
        if s is None:
            ep = self._peer_eps.get(rank)
            if ep is None:
                raise KeyError(f"no endpoint registered for rank {rank}")
            host, port = ep.rsplit(":", 1)
            deadline = time.monotonic() + 30
            while True:
                try:
                    s = socket.create_connection((host, int(port)),
                                                 timeout=30)
                    break
                except ConnectionRefusedError:
                    # peers race to listen() at startup
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._peer_socks[rank] = s
        return s

    def send(self, msg: Message):
        dst_rank = self._task_ranks.get(msg.dst_id, self.rank)
        if dst_rank == self.rank:
            self._deliver_local(msg)
            return
        from .ps import _send_msg

        lock = self._peer_locks.setdefault(dst_rank, threading.Lock())
        frame = {"src": msg.src_id, "dst": msg.dst_id, "type": msg.type,
                 "payload": msg.payload, "scope": msg.scope_idx}
        with lock:
            _send_msg(self._peer(dst_rank), frame)


class Interceptor(threading.Thread):
    """interceptor.h: an actor — one thread, one inbox, a handle() loop."""

    def __init__(self, node: TaskNode, bus: MessageBus):
        super().__init__(daemon=True)
        self.node = node
        self.bus = bus
        self.inbox = bus.register(node.task_id)
        self.error: Optional[BaseException] = None

    def send(self, dst_id: int, type_: str, payload=None, scope_idx=0):
        self.bus.send(Message(self.node.task_id, dst_id, type_, payload,
                              scope_idx))

    def handle(self, msg: Message):
        raise NotImplementedError

    def run(self):
        while True:
            _, _, msg = self.inbox.get()
            if msg.type == _STOP:
                return
            try:
                self.handle(msg)
            except BaseException as e:
                self.error = e
                return

    def stop(self):
        self.inbox.put((1, next(_seq), Message(-1, self.node.task_id, _STOP)))


class ComputeInterceptor(Interceptor):
    """compute_interceptor.cc: on each upstream DATA message run the stage
    fn and forward; DONE propagates when every upstream finished.

    Flow control (compute_interceptor.cc UpStream/DownStream buffs): each
    downstream edge starts with `credit_of[d]` send credits (the
    downstream's max_run_times). A micro-batch is PROCESSED only when
    every downstream edge has a credit — consuming runs fn, acks the
    upstream with a CREDIT frame, and forwards, exactly the reference's
    "ready = input available AND output buffer space" gate, so
    backpressure propagates hop-by-hop instead of pooling unbounded
    payloads at a fast stage. DONE defers behind any still-queued data so
    it can never overtake the last micro-batch."""

    def __init__(self, node: TaskNode, bus: MessageBus,
                 sink_queue: Optional["queue_mod.Queue"] = None,
                 credit_of: Optional[Dict[int, int]] = None):
        super().__init__(node, bus)
        self._done_from = set()
        self._sink_queue = sink_queue
        credit_of = credit_of or {}
        self._credit = {d: max(1, int(credit_of.get(d, 1)))
                        for d in node.downstream}
        self._pending_in: "collections.deque" = collections.deque()
        self._done_pending = False
        self._finished = False

    def _can_send(self) -> bool:
        return all(c > 0 for c in self._credit.values())

    def _drain(self):
        while self._pending_in and self._can_send():
            src, payload, scope = self._pending_in.popleft()
            out = payload
            if self.node.fn is not None:
                out = self.node.fn(out)
            if src >= 0:
                self.send(src, CREDIT)  # consumed AND forwardable: ack
            for d in self.node.downstream:
                self._credit[d] -= 1
                self.send(d, DATA, out, scope)
            if self._sink_queue is not None:
                self._sink_queue.put((DATA, out))

    def _maybe_finish(self):
        if self._finished or not self._done_pending or self._pending_in:
            return
        self._finished = True
        for d in self.node.downstream:
            self.send(d, DONE)
        if self._sink_queue is not None:
            self._sink_queue.put((DONE, None))
        self.stop()

    def handle(self, msg: Message):
        if msg.type == CREDIT:
            if msg.src_id in self._credit:
                self._credit[msg.src_id] += 1
            self._drain()
            self._maybe_finish()
            return
        if msg.type == DONE:
            self._done_from.add(msg.src_id)
            if self._done_from >= set(self.node.upstream):
                self._done_pending = True
                self._maybe_finish()
            return
        if msg.type != DATA:
            return
        self._pending_in.append((msg.src_id, msg.payload, msg.scope_idx))
        self._drain()


class ServiceInterceptor(Interceptor):
    """Request/reply actor over the bus (ISSUE 20): the server half of an
    RPC seam the pipeline's sharded PS hosts ride — the reference's
    brpc PsService role, rebuilt on the MessageBus actor plane so the
    same service runs in-process (tests) and cross-host (TLV framing)
    without a second transport.

    `methods` maps name -> fn(**kwargs) -> wire-packable payload. Errors
    are caught and shipped back as a structured failure (the caller
    re-raises); they never kill the actor thread, so one bad request
    cannot take a shard host down."""

    def __init__(self, node: TaskNode, bus: MessageBus,
                 methods: Dict[str, Callable]):
        super().__init__(node, bus)
        self.methods = dict(methods)

    def handle(self, msg: Message):
        if msg.type != REQUEST:
            return
        p = msg.payload
        try:
            fn = self.methods[p["m"]]
            rep = {"req": p["req"], "ok": True, "out": fn(**(p.get("kw") or {}))}
        except BaseException as e:
            rep = {"req": p["req"], "ok": False,
                   "err": f"{type(e).__name__}: {e}"}
        self.bus.send(Message(self.node.task_id, int(p["reply_to"]), REPLY,
                              rep))


class RemoteCallError(RuntimeError):
    """The service executed the request and reported a failure."""


class BusRpcClient:
    """Caller half of the bus RPC seam: owns one inbox task id, demuxes
    replies by request id, blocks each call() under a per-attempt timeout
    (the PR-4 failure model's retry/backoff lives in the caller — this
    class only says *timed out*, loudly and typed)."""

    def __init__(self, bus: MessageBus, task_id: int):
        self.bus = bus
        self.task_id = int(task_id)
        self.inbox = bus.register(self.task_id)
        self._pending: Dict[int, dict] = {}
        self._lock = threading.Lock()
        self._req_ids = itertools.count(1)
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._rx.start()

    def _recv_loop(self):
        while True:
            _, _, msg = self.inbox.get()
            if msg.type == _STOP:
                return
            if msg.type != REPLY:
                continue
            p = msg.payload
            with self._lock:
                slot = self._pending.pop(int(p["req"]), None)
            if slot is not None:  # late reply after timeout: dropped
                slot["rep"] = p
                slot["ev"].set()

    def call(self, dst_task: int, method: str,
             timeout: Optional[float] = None, **kw):
        req = next(self._req_ids)
        slot = {"ev": threading.Event()}
        with self._lock:
            self._pending[req] = slot
        self.bus.send(Message(self.task_id, int(dst_task), REQUEST,
                              {"req": req, "m": method,
                               "reply_to": self.task_id, "kw": kw}))
        if not slot["ev"].wait(timeout):
            with self._lock:
                self._pending.pop(req, None)
            raise TimeoutError(
                f"bus rpc {method!r} to task {dst_task} timed out "
                f"after {timeout}s")
        rep = slot["rep"]
        if not rep["ok"]:
            raise RemoteCallError(
                f"task {dst_task} {method!r} failed remotely: {rep['err']}")
        return rep["out"]

    def close(self):
        self.inbox.put((1, next(_seq),
                        Message(-1, self.task_id, _STOP)))
        self._rx.join(timeout=5)


class Carrier:
    """carrier.cc: hosts this rank's interceptors over a shared bus."""

    def __init__(self, rank: int, bus: Optional[MessageBus] = None):
        self.rank = rank
        self.bus = bus or MessageBus(rank=rank)
        self.interceptors: Dict[int, Interceptor] = {}
        self.sink_queue: "queue_mod.Queue" = queue_mod.Queue()

    def add_task(self, node: TaskNode,
                 credit_of: Optional[Dict[int, int]] = None):
        sink = self.sink_queue if not node.downstream else None
        ic = ComputeInterceptor(node, self.bus, sink_queue=sink,
                                credit_of=credit_of)
        self.interceptors[node.task_id] = ic
        return ic

    def start(self):
        for ic in self.interceptors.values():
            ic.start()

    def wait(self, timeout=60):
        """Join every interceptor within ONE overall timeout; raises
        TimeoutError if any stage is still running (a hung drain must not
        read as success) and re-raises the first stage error."""
        deadline = time.monotonic() + timeout
        for ic in self.interceptors.values():
            ic.join(timeout=max(0.0, deadline - time.monotonic()))
            if ic.error is not None:
                raise ic.error
            if ic.is_alive():
                raise TimeoutError(
                    f"interceptor for task {ic.node.task_id} still "
                    f"running after {timeout}s")

    def stop(self):
        for ic in self.interceptors.values():
            ic.stop()


class FleetExecutor:
    """fleet_executor.cc: build the task graph, run micro-batches through.

        exe = FleetExecutor([TaskNode(0, fn=preproc, downstream=[1]),
                             TaskNode(1, fn=predictor, downstream=[2]),
                             TaskNode(2, fn=postproc)])
        outs = exe.run(list_of_microbatches)

    Cross-host: give each TaskNode a `rank`; every process builds the SAME
    global graph with its own `rank=` and exchanges bus endpoints
    (`exe.endpoint()` / `exe.connect(rank, ep)`). run() feeds sources on
    the rank that hosts them and returns sink outputs on the rank that
    hosts the sink ([] elsewhere — use wait() to block until the local
    stages drain). Matches the reference's one-section-per-rank carriers
    over the brpc bus (fleet_executor.cc + message_bus.cc)."""

    def __init__(self, task_nodes: List[TaskNode], rank: int = 0):
        by_id = {t.task_id: t for t in task_nodes}
        for t in task_nodes:
            for d in t.downstream:
                if t.task_id not in by_id[d].upstream:
                    by_id[d].upstream.append(t.task_id)
        self.nodes = task_nodes
        self.rank = int(rank)
        task_ranks = {t.task_id: int(t.rank) for t in task_nodes}
        credit_of = {t.task_id: t.max_run_times for t in task_nodes}
        bus = MessageBus(rank=self.rank, task_ranks=task_ranks)
        self.carrier = Carrier(rank=self.rank, bus=bus)
        self._local = [t for t in task_nodes if int(t.rank) == self.rank]
        for t in self._local:
            self.carrier.add_task(t, credit_of=credit_of)
        self._sources = [t.task_id for t in self._local if not t.upstream]
        self._sink_local = any(not t.downstream for t in self._local)
        self._started = False

    # ---- cross-host wiring -------------------------------------------
    def endpoint(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Start this rank's bus listener; returns "host:port" to hand to
        the other ranks' connect()."""
        return self.carrier.bus.listen(host, port)

    def connect(self, rank: int, endpoint: str):
        self.carrier.bus.connect(rank, endpoint)

    def run(self, microbatches: List[Any], timeout=120) -> List[Any]:
        if not self._started:
            self.carrier.start()
            self._started = True
        bus = self.carrier.bus
        for i, mb in enumerate(microbatches):
            for s in self._sources:
                bus.send(Message(-1, s, DATA, mb, scope_idx=i))
        if not self._sink_local:
            return []
        outs = []
        expect = len(microbatches)
        while len(outs) < expect:
            kind, payload = self.carrier.sink_queue.get(timeout=timeout)
            for ic in self.carrier.interceptors.values():
                if ic.error is not None:
                    raise ic.error
            if kind == DATA:
                outs.append(payload)
        return outs

    def wait(self, timeout=120):
        """Block until every local interceptor has drained (DONE seen)."""
        self.carrier.wait(timeout=timeout)

    def shutdown(self, timeout=60):
        # source-first DONE flood, then wait for the drain: interceptors
        # exit via DONE propagation only after flushing their queued
        # micro-batches (credits may still need to cross the wire), so
        # the bus must stay open until local stages have finished —
        # and must be torn down even when a stage errored or hung
        try:
            if self._started:
                for s in self._sources:
                    self.carrier.bus.send(Message(-1, s, DONE))
                self.carrier.wait(timeout=timeout)
        finally:
            self.carrier.stop()  # safety net for a stage stuck past timeout
            self.carrier.bus.close()


class DistModelConfig:
    """Configuration for distributed inference (reference:
    fleet_executor/dist_model.h DistModelConfig: model_dir, ranks,
    trainer_endpoints). TPU framing: `batch_axis` names the mesh axis the
    feed batch is split over."""

    def __init__(self, model_dir=None, model_prefix=None, batch_axis="data",
                 place=None, nranks=1, rank=0, trainer_endpoints=None):
        self.model_prefix = model_prefix or model_dir
        self.batch_axis = batch_axis
        self.place = place
        self.nranks = nranks
        self.rank = rank
        self.trainer_endpoints = trainer_endpoints or []


class DistModel:
    """Distributed inference over the active mesh (reference:
    fleet_executor/dist_model.cc: per-rank program load + fleet-executor
    run; here GSPMD: ONE artifact, weights replicated, the batch sharded
    over `batch_axis`, XLA inserting any collectives).

    Usage:
        cfg = DistModelConfig(model_prefix="/path/prefix")
        m = DistModel(cfg); m.init()
        outs = m.run(feeds)   # list of np arrays in manifest feed order
    """

    def __init__(self, config: DistModelConfig):
        self.config = config
        self._artifact = None
        self._batch_sharding = None
        self._mesh = None

    def init(self):
        from ..inference.io import InferenceArtifact

        self._artifact = InferenceArtifact.load(self.config.model_prefix)
        self._refresh_mesh()
        return True

    def _refresh_mesh(self):
        """(Re)bind weights and the batch sharding to the CURRENT mesh —
        called from run() too, so a mesh set or replaced after init() is
        honored rather than crashing or sharding onto a stale mesh."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from . import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        if m is self._mesh:
            return
        self._mesh = m
        if m is None or m.size == 1:
            self._batch_sharding = None
            return
        rep = NamedSharding(m, P())
        self._artifact.weights = [jax.device_put(w, rep)
                                  for w in self._artifact.weights]
        self._batch_sharding = NamedSharding(
            m, mesh_mod.sanitize_spec(P(self.config.batch_axis), m))

    def run(self, feeds):
        """feeds: list of arrays in manifest feed order (or dict by name).
        The leading batch dim of every feed is sharded over batch_axis."""
        import jax
        import numpy as np

        art = self._artifact
        if art is None:
            raise RuntimeError("DistModel.init() must run first")
        if isinstance(feeds, dict):
            feeds = [feeds[n] for n in art.feed_names]
        self._refresh_mesh()
        vals = []
        for v in feeds:
            a = np.asarray(v)
            if self._batch_sharding is not None and a.ndim > 0:
                a = jax.device_put(a, self._batch_sharding)
            vals.append(a)
        outs = art.run(vals)
        return [np.asarray(o) for o in outs]

"""Fleet executor — the actor-style control plane for distributed inference.

Reference: paddle/fluid/distributed/fleet_executor/ (~8k LoC C++):
FleetExecutor builds a task graph of TaskNodes, a Carrier per rank hosts
Interceptors (actors) that exchange messages over a MessageBus, and
micro-batches flow source → compute stages → sink with credit-based flow
control (compute_interceptor.cc UpSteam/DownStream buffs).

TPU-native framing: the DATA plane of multi-stage inference is the SPMD
pipeline (distributed/pipeline.py) — XLA moves activations over ICI. What
the fleet executor keeps is the HOST control plane: asynchronous stage
orchestration for host-resident steps (pre/post-processing, PS lookups,
detokenization) around compiled programs. Actors are threads with
queues; the MessageBus routes by task id and is process-local here (the
cross-host hop would ride the same socket transport as distributed/ps).
"""
from __future__ import annotations

import queue as queue_mod
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["TaskNode", "Interceptor", "ComputeInterceptor", "Carrier",
           "MessageBus", "FleetExecutor",
           "DistModel", "DistModelConfig"]

_STOP = "__stop__"
DATA = "data"
DONE = "done"


@dataclass
class Message:
    src_id: int
    dst_id: int
    type: str
    payload: Any = None
    scope_idx: int = 0


@dataclass
class TaskNode:
    """fleet_executor/task_node.h: one stage of the task graph."""

    task_id: int
    rank: int = 0
    max_run_times: int = 1  # micro-batch concurrency credit
    fn: Optional[Callable] = None  # the stage computation (compiled program)
    downstream: List[int] = field(default_factory=list)
    upstream: List[int] = field(default_factory=list)
    role: str = "compute"  # source | compute | sink


class MessageBus:
    """interceptor_message_service.cc analog: task-id → inbox routing."""

    def __init__(self):
        self._inboxes: Dict[int, "queue_mod.Queue"] = {}
        self._lock = threading.Lock()

    def register(self, task_id: int) -> "queue_mod.Queue":
        with self._lock:
            q = queue_mod.Queue()
            self._inboxes[task_id] = q
            return q

    def send(self, msg: Message):
        with self._lock:
            box = self._inboxes.get(msg.dst_id)
        if box is None:
            raise KeyError(f"no interceptor registered for task "
                           f"{msg.dst_id}")
        box.put(msg)


class Interceptor(threading.Thread):
    """interceptor.h: an actor — one thread, one inbox, a handle() loop."""

    def __init__(self, node: TaskNode, bus: MessageBus):
        super().__init__(daemon=True)
        self.node = node
        self.bus = bus
        self.inbox = bus.register(node.task_id)
        self.error: Optional[BaseException] = None

    def send(self, dst_id: int, type_: str, payload=None, scope_idx=0):
        self.bus.send(Message(self.node.task_id, dst_id, type_, payload,
                              scope_idx))

    def handle(self, msg: Message):
        raise NotImplementedError

    def run(self):
        while True:
            msg = self.inbox.get()
            if msg.type == _STOP:
                return
            try:
                self.handle(msg)
            except BaseException as e:
                self.error = e
                return

    def stop(self):
        self.inbox.put(Message(-1, self.node.task_id, _STOP))


class ComputeInterceptor(Interceptor):
    """compute_interceptor.cc: on each upstream DATA message run the stage
    fn and forward; DONE propagates when every upstream finished."""

    def __init__(self, node: TaskNode, bus: MessageBus,
                 sink_queue: Optional["queue_mod.Queue"] = None):
        super().__init__(node, bus)
        self._done_from = set()
        self._sink_queue = sink_queue

    def handle(self, msg: Message):
        if msg.type == DONE:
            self._done_from.add(msg.src_id)
            if self._done_from >= set(self.node.upstream):
                for d in self.node.downstream:
                    self.send(d, DONE)
                if self._sink_queue is not None:
                    self._sink_queue.put((DONE, None))
                self.stop()
            return
        if msg.type != DATA:
            return
        out = msg.payload
        if self.node.fn is not None:
            out = self.node.fn(out)
        for d in self.node.downstream:
            self.send(d, DATA, out, msg.scope_idx)
        if self._sink_queue is not None:
            self._sink_queue.put((DATA, out))


class Carrier:
    """carrier.cc: hosts this rank's interceptors over a shared bus."""

    def __init__(self, rank: int, bus: Optional[MessageBus] = None):
        self.rank = rank
        self.bus = bus or MessageBus()
        self.interceptors: Dict[int, Interceptor] = {}
        self.sink_queue: "queue_mod.Queue" = queue_mod.Queue()

    def add_task(self, node: TaskNode):
        sink = self.sink_queue if not node.downstream else None
        ic = ComputeInterceptor(node, self.bus, sink_queue=sink)
        self.interceptors[node.task_id] = ic
        return ic

    def start(self):
        for ic in self.interceptors.values():
            ic.start()

    def wait(self, timeout=60):
        for ic in self.interceptors.values():
            ic.join(timeout=timeout)
            if ic.error is not None:
                raise ic.error

    def stop(self):
        for ic in self.interceptors.values():
            ic.stop()


class FleetExecutor:
    """fleet_executor.cc: build the task graph, run micro-batches through.

        exe = FleetExecutor([TaskNode(0, fn=preproc, downstream=[1]),
                             TaskNode(1, fn=predictor, downstream=[2]),
                             TaskNode(2, fn=postproc)])
        outs = exe.run(list_of_microbatches)
    """

    def __init__(self, task_nodes: List[TaskNode]):
        by_id = {t.task_id: t for t in task_nodes}
        for t in task_nodes:
            for d in t.downstream:
                if t.task_id not in by_id[d].upstream:
                    by_id[d].upstream.append(t.task_id)
        self.nodes = task_nodes
        self.carrier = Carrier(rank=0)
        for t in task_nodes:
            self.carrier.add_task(t)
        self._sources = [t.task_id for t in task_nodes if not t.upstream]
        self._started = False

    def run(self, microbatches: List[Any], timeout=120) -> List[Any]:
        if not self._started:
            self.carrier.start()
            self._started = True
        bus = self.carrier.bus
        for i, mb in enumerate(microbatches):
            for s in self._sources:
                bus.send(Message(-1, s, DATA, mb, scope_idx=i))
        outs = []
        expect = len(microbatches)
        while len(outs) < expect:
            kind, payload = self.carrier.sink_queue.get(timeout=timeout)
            for ic in self.carrier.interceptors.values():
                if ic.error is not None:
                    raise ic.error
            if kind == DATA:
                outs.append(payload)
        return outs

    def shutdown(self):
        # source-first DONE flood drains the graph
        for s in self._sources:
            self.carrier.bus.send(Message(-1, s, DONE))
        self.carrier.stop()


class DistModelConfig:
    """Configuration for distributed inference (reference:
    fleet_executor/dist_model.h DistModelConfig: model_dir, ranks,
    trainer_endpoints). TPU framing: `batch_axis` names the mesh axis the
    feed batch is split over."""

    def __init__(self, model_dir=None, model_prefix=None, batch_axis="data",
                 place=None, nranks=1, rank=0, trainer_endpoints=None):
        self.model_prefix = model_prefix or model_dir
        self.batch_axis = batch_axis
        self.place = place
        self.nranks = nranks
        self.rank = rank
        self.trainer_endpoints = trainer_endpoints or []


class DistModel:
    """Distributed inference over the active mesh (reference:
    fleet_executor/dist_model.cc: per-rank program load + fleet-executor
    run; here GSPMD: ONE artifact, weights replicated, the batch sharded
    over `batch_axis`, XLA inserting any collectives).

    Usage:
        cfg = DistModelConfig(model_prefix="/path/prefix")
        m = DistModel(cfg); m.init()
        outs = m.run(feeds)   # list of np arrays in manifest feed order
    """

    def __init__(self, config: DistModelConfig):
        self.config = config
        self._artifact = None
        self._batch_sharding = None
        self._mesh = None

    def init(self):
        from ..inference.io import InferenceArtifact

        self._artifact = InferenceArtifact.load(self.config.model_prefix)
        self._refresh_mesh()
        return True

    def _refresh_mesh(self):
        """(Re)bind weights and the batch sharding to the CURRENT mesh —
        called from run() too, so a mesh set or replaced after init() is
        honored rather than crashing or sharding onto a stale mesh."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from . import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        if m is self._mesh:
            return
        self._mesh = m
        if m is None or m.size == 1:
            self._batch_sharding = None
            return
        rep = NamedSharding(m, P())
        self._artifact.weights = [jax.device_put(w, rep)
                                  for w in self._artifact.weights]
        self._batch_sharding = NamedSharding(
            m, mesh_mod.sanitize_spec(P(self.config.batch_axis), m))

    def run(self, feeds):
        """feeds: list of arrays in manifest feed order (or dict by name).
        The leading batch dim of every feed is sharded over batch_axis."""
        import jax
        import numpy as np

        art = self._artifact
        if art is None:
            raise RuntimeError("DistModel.init() must run first")
        if isinstance(feeds, dict):
            feeds = [feeds[n] for n in art.feed_names]
        self._refresh_mesh()
        vals = []
        for v in feeds:
            a = np.asarray(v)
            if self._batch_sharding is not None and a.ndim > 0:
                a = jax.device_put(a, self._batch_sharding)
            vals.append(a)
        outs = art.run(vals)
        return [np.asarray(o) for o in outs]

"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Communication is mesh-sharding + XLA collectives, not process-side NCCL ops;
the reference's API surface (collective functions, fleet, launch) is preserved
on top. See SURVEY.md §2.2/§2.3 for the mapping.
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, get_group, new_group,
    recv, reduce, ReduceOp, scatter, send, split, wait,
)
from .parallel import DataParallel  # noqa: F401
from . import fleet  # noqa: F401
from .mesh import get_mesh, set_mesh, default_mesh  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, dtensor_from_fn,
    reshard, shard_layer, shard_tensor,
)
from .ring_attention import ring_attention  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .utils import global_gather, global_scatter  # noqa: F401

QUEUE_TIMEOUT = 30


def get_world_size_fn():
    return get_world_size()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn (distributed/spawn.py:568) — multiprocess
    launcher. On TPU a single process drives all local chips through the mesh,
    so spawn degenerates to an in-process call for nprocs<=1; true multi-host
    uses `python -m paddle_tpu.distributed.launch`."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        import os

        env = dict(os.environ, PADDLE_TRAINER_ID=str(rank),
                   PADDLE_TRAINERS_NUM=str(nprocs))

        def target(r=rank, e=env):
            import os as _os

            _os.environ.update(e)
            func(*args)

        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs

"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Communication is mesh-sharding + XLA collectives, not process-side NCCL ops;
the reference's API surface (collective functions, fleet, launch) is preserved
on top. See SURVEY.md §2.2/§2.3 for the mapping.
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, get_group, new_group,
    recv, reduce, reduce_scatter, ReduceOp, scatter, send, split, wait,
)
from .parallel import DataParallel  # noqa: F401
from . import grad_comm  # noqa: F401
from .grad_comm import GradCommConfig, GradCommunicator  # noqa: F401
from . import overlap  # noqa: F401
from .overlap import OverlappedGradCommunicator  # noqa: F401
from . import fleet  # noqa: F401
from .mesh import get_mesh, set_mesh, default_mesh  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import metric  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, dtensor_from_fn,
    reshard, shard_layer, shard_tensor,
)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .utils import global_gather, global_scatter  # noqa: F401

QUEUE_TIMEOUT = 30


def get_world_size_fn():
    return get_world_size()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn (distributed/spawn.py:568) — multiprocess
    launcher. On TPU a single process drives all local chips through the mesh,
    so spawn degenerates to an in-process call for nprocs<=1; true multi-host
    uses `python -m paddle_tpu.distributed.launch`."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        import os

        env = dict(os.environ, PADDLE_TRAINER_ID=str(rank),
                   PADDLE_TRAINERS_NUM=str(nprocs))

        def target(r=rank, e=env):
            import os as _os

            _os.environ.update(e)
            func(*args)

        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


# -------------------------------------------------- reference-parity tail
from . import launch  # noqa: F401,E402
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401,E402


class _TableEntry:
    """Sparse-table entry-filter config (reference:
    distributed/entry_attr.py): controls when a feature id becomes a real
    table row. Consumed by sparse_embedding's `entry` argument; the native
    table applies show-count decay on shrink."""

    def __repr__(self):
        return self.to_attr()


class CountFilterEntry(_TableEntry):
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def to_attr(self):
        return f"count_filter_entry:{self.count_filter}"


class ProbabilityEntry(_TableEntry):
    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def to_attr(self):
        return f"probability_entry:{self.probability}"


class ShowClickEntry(_TableEntry):
    def __init__(self, show_name, click_name):
        self.show_name = str(show_name)
        self.click_name = str(click_name)

    def to_attr(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Host-side (CPU) collective context init (reference:
    distributed/collective.py gloo_init_parallel_env over gloo). The TPU
    build's host barrier/collectives ride the PS wire protocol — the
    server_endpoint names a PsServer used as the rendezvous."""
    import os as _os

    _os.environ["PADDLE_GLOO_RENDEZVOUS"] = server_endpoint
    _os.environ["PADDLE_TRAINER_ID"] = str(rank_id)
    _os.environ["PADDLE_TRAINERS_NUM"] = str(rank_num)


def gloo_barrier():
    """CPU barrier over the gloo-analog rendezvous (reference:
    distributed/collective.py gloo_barrier). Single-process: no peers to
    wait for; multi-process setups barrier through the PS server named by
    gloo_init_parallel_env."""
    import os as _os

    ep = _os.environ.get("PADDLE_GLOO_RENDEZVOUS")
    n = int(_os.environ.get("PADDLE_TRAINERS_NUM", 1))
    if not ep or n <= 1:
        return
    from .ps import PsClient

    cli = PsClient([ep])
    cli.barrier(group="gloo", n=n)
    cli.close()


def gloo_release():
    import os as _os

    _os.environ.pop("PADDLE_GLOO_RENDEZVOUS", None)

"""Expert-parallel routing utilities.

Reference: python/paddle/distributed/utils.py:57 (global_scatter) and :179
(global_gather) — NCCL alltoall ops moving variable token counts between
n_expert * world_size experts (operators/collective/global_scatter_op.cc).

TPU-native note: variable-count alltoall implies data-dependent shapes, which
XLA cannot compile; the production EP path is distributed.moe.MoELayer
(fixed-capacity GShard routing whose dispatch einsum GSPMD lowers to AllToAll).
These functions keep the reference API: they implement the exact routing
permutation semantics eagerly (host-computed counts), which is also how the
reference's unit tests exercise the ops (test_collective_global_scatter.py
compares against NumPy semantics).
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor, to_tensor


def _counts(c):
    if isinstance(c, Tensor):
        return c.numpy().astype("int64")
    return np.asarray(c, dtype="int64")


def _ep_axis(group):
    """Mesh axis carrying the expert-parallel world (group maps to an axis
    name; default 'data' — tokens and experts ride the data axis, as the
    reference's default EP group spans all ranks)."""
    from . import mesh as mesh_mod

    axis = group if isinstance(group, str) else "data"
    m = mesh_mod.get_mesh()
    if m is None or axis not in m.axis_names or m.shape[axis] == 1:
        return None, 1
    return axis, int(m.shape[axis])


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Route rows of ``x`` to n_expert * world experts.

    local_count[i]: #rows this rank sends to expert (i % n_expert) of rank
    (i // n_expert); global_count[i]: #rows this rank receives for its local
    expert (i % n_expert) from rank (i // n_expert).

    Multi-device (mesh axis present): a REAL AllToAll over the ICI via
    shard_map — requires device-uniform counts (XLA needs static shapes;
    ragged routing is what MoELayer's fixed-capacity dispatch exists for).
    world == 1: the permutation is the identity by construction.
    """
    lc, gc = _counts(local_count), _counts(global_count)
    if int(lc.sum()) != int(x.shape[0]) and _ep_axis(group)[1] == 1:
        raise ValueError(
            f"local_count sums to {int(lc.sum())} but x has {x.shape[0]} rows")
    axis, world = _ep_axis(group)
    if world == 1:
        if int(gc.sum()) != int(lc.sum()):
            raise ValueError(
                "global_count must receive every sent row when world==1")
        return x.clone()

    import jax
    import jax.numpy as jnp

    from . import mesh as mesh_mod
    from ..framework.autograd import call_op

    n_expert = lc.size // world
    if lc.size % world or len(set(lc.tolist())) != 1:
        raise NotImplementedError(
            "multi-device global_scatter requires device-uniform counts "
            "(static shapes); use distributed.MoELayer for ragged routing")
    c = int(lc[0])
    m = mesh_mod.get_mesh()
    from jax.sharding import PartitionSpec as P

    spec = P(axis, None)

    def body(xl):
        # xl: [world*n_expert*c, d] send-ordered (rank-major, expert-minor)
        d = xl.shape[-1]
        send = xl.reshape(world, n_expert * c, d)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
        # received[r] = the block rank r sent me → regroup expert-major
        out = recv.reshape(world, n_expert, c, d).transpose(1, 0, 2, 3)
        return out.reshape(world * n_expert * c, d)

    fn = mesh_mod.compat_shard_map(body, m, (spec,), spec)
    return call_op(fn, x, op_name="global_scatter")


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: return expert outputs to the token owners."""
    lc, gc = _counts(local_count), _counts(global_count)
    axis, world = _ep_axis(group)
    if world == 1:
        if int(gc.sum()) != int(x.shape[0]):
            raise ValueError(
                f"global_count sums to {int(gc.sum())} but x has "
                f"{x.shape[0]} rows")
        return x.clone()

    import jax
    import jax.numpy as jnp

    from . import mesh as mesh_mod
    from ..framework.autograd import call_op

    n_expert = lc.size // world
    if lc.size % world or len(set(lc.tolist())) != 1:
        raise NotImplementedError(
            "multi-device global_gather requires device-uniform counts; "
            "use distributed.MoELayer for ragged routing")
    c = int(lc[0])
    m = mesh_mod.get_mesh()
    from jax.sharding import PartitionSpec as P

    spec = P(axis, None)

    def body(xl):
        d = xl.shape[-1]
        # xl is expert-major [n_expert, world, c, d]: undo the regroup...
        send = xl.reshape(n_expert, world, c, d).transpose(1, 0, 2, 3)
        send = send.reshape(world, n_expert * c, d)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
        return recv.reshape(world * n_expert * c, d)

    fn = mesh_mod.compat_shard_map(body, m, (spec,), spec)
    return call_op(fn, x, op_name="global_gather")

"""Expert-parallel routing utilities.

Reference: python/paddle/distributed/utils.py:57 (global_scatter) and :179
(global_gather) — NCCL alltoall ops moving variable token counts between
n_expert * world_size experts (operators/collective/global_scatter_op.cc).

TPU-native note: variable-count alltoall implies data-dependent shapes, which
XLA cannot compile; the production EP path is distributed.moe.MoELayer
(fixed-capacity GShard routing whose dispatch einsum GSPMD lowers to AllToAll).
These functions keep the reference API: they implement the exact routing
permutation semantics eagerly (host-computed counts), which is also how the
reference's unit tests exercise the ops (test_collective_global_scatter.py
compares against NumPy semantics).
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor, to_tensor


def _counts(c):
    if isinstance(c, Tensor):
        return c.numpy().astype("int64")
    return np.asarray(c, dtype="int64")


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Route rows of ``x`` to n_expert * world experts.

    local_count[i]: #rows this rank sends to expert (i % n_expert) of rank
    (i // n_expert); global_count[i]: #rows this rank receives for its local
    expert (i % n_expert) from rank (i // n_expert). Single-process runtime:
    world == 1, so the received layout is the expert-major grouping of x's
    rows (x is expected expert-grouped by local_count, as in the reference).
    """
    lc, gc = _counts(local_count), _counts(global_count)
    if int(lc.sum()) != int(x.shape[0]):
        raise ValueError(
            f"local_count sums to {int(lc.sum())} but x has {x.shape[0]} rows")
    # world==1: sending order == receiving order; output is x with rows for
    # each local expert contiguous — already true by construction.
    if int(gc.sum()) != int(lc.sum()):
        raise ValueError("global_count must receive every sent row when world==1")
    return x.clone()


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter: return expert outputs to the token owners.
    world==1: the inverse permutation is the identity."""
    lc, gc = _counts(local_count), _counts(global_count)
    if int(gc.sum()) != int(x.shape[0]):
        raise ValueError(
            f"global_count sums to {int(gc.sum())} but x has {x.shape[0]} rows")
    return x.clone()

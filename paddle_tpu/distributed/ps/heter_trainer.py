"""Heter-PS pass trainer: the PSGPUTrainer drive loop over DevicePassCache.

Reference: PSGPUTrainer / HeterXpuTrainer (paddle/fluid/framework/
trainer.h:179,249) and ps_gpu_wrapper.cc BuildGPUTask: each training PASS
bulk-pulls its sparse working set into device memory, every in-pass lookup
is a device gather (no per-batch host-PS hop), and the merged gradients
push back once at pass end (downpour semantics: one optimizer step per
pass per key with the summed gradient).

TPU-native: DevicePassCache holds the rows as one jnp array; lookups fuse
into the jitted step as XLA gathers. heter_embedding() is the drop-in for
distributed_lookup_table inside the step — same Tensor-with-grad surface,
but backward scatter-adds into the device accumulator instead of a host
push per step.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from .heter_cache import DevicePassCache

__all__ = ["HeterPassTrainer", "heter_embedding"]


def heter_embedding(cache, ids):
    """Cache-backed embedding lookup with gradient accumulation.

    Works over either cache tier: the pass-scoped DevicePassCache (rows
    pulled once by begin_pass) or the capacity-bounded HeterCache (LRU/LFU
    with batched faults). Forward: device gather. Backward: device
    scatter-add into the cache's grad accumulator — the host PS sees
    merged pushes at end_pass/flush/eviction, not one per step
    (ps_gpu_wrapper.cc push_sparse-at-EndPass semantics).
    """
    import jax
    import jax.numpy as jnp

    from ...framework import autograd
    from ...framework.tensor import Tensor

    ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
    if isinstance(cache, DevicePassCache):
        slot_idx = cache.slots(ids_np)  # one host translation per batch
        out_val = cache.lookup_slots(jnp.asarray(slot_idx))

        def backward(cot, dim):
            cache._push_slot_grads(slot_idx.reshape(-1),
                                   np.asarray(cot).reshape(-1, dim))
    else:  # HeterCache: faulting lookup; grads keyed by id
        out_val = cache.lookup(ids_np)

        def backward(cot, dim):
            cache.push_grads(ids_np.reshape(-1),
                             np.asarray(cot).reshape(-1, dim))

    out = Tensor(out_val, _internal=True)
    if autograd.is_grad_enabled():
        dim = out_val.shape[-1]

        def vjp_fn(cot):
            backward(cot, dim)
            return []

        node = autograd.GradNode(
            vjp_fn, [],
            [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
            multi_output=False, name="heter_embedding")
        out.stop_gradient = False
        out._grad_node = node
        out._out_index = 0
    return out


class HeterPassTrainer:
    """Drives train_from_dataset with the pass lifecycle of PSGPUTrainer.

    step_fn(cache, batch) runs one mini-batch (typically: heter_embedding
    lookups + dense forward/backward + dense optimizer step); the trainer
    owns BuildGPUTask (working-set union + ONE bulk pull) before the pass
    and the merged push after it.
    """

    def __init__(self, client, table_id: int, lr: float = -1.0,
                 sparse_slots: Sequence[int] = (0,)):
        self.cache = DevicePassCache(client, table_id, lr=lr)
        self.sparse_slots = tuple(sparse_slots)

    def _pass_ids(self, batches):
        return np.concatenate(
            [np.asarray(b[s], np.uint64).reshape(-1)
             for b in batches for s in self.sparse_slots])

    def train_from_dataset(self, dataset, step_fn: Callable, passes: int = 1,
                           pad_to=None):
        """One or more passes over `dataset`. Per pass: BuildGPUTask
        (materialize the pass, union its sparse ids, one bulk pull),
        per-batch device-gather steps, EndPass sync. Returns the last
        pass's step_fn outputs.

        The end-of-pass sync mode follows the step_fn: a CompiledPassStep
        with a device-side table optimizer writes VALUES back
        (assign=True) — its gacc holds optimizer state, which must never
        be pushed as a gradient; every other step_fn pushes the merged
        gradient (downpour)."""
        assign = bool(getattr(step_fn, "table_optimizer", None))
        outs = []
        for _ in range(int(passes)):
            batches = list(dataset.iterate())
            if not batches:
                return outs
            self.cache.begin_pass(self._pass_ids(batches), pad_to=pad_to)
            try:
                outs = [step_fn(self.cache, b) for b in batches]
            finally:
                self.cache.end_pass(assign=assign)
        return outs

    def infer_from_dataset(self, dataset, step_fn: Callable):
        """Evaluation twin: pull the working set, run step_fn per batch
        (no grads accumulate -> end_pass pushes nothing)."""
        batches = list(dataset.iterate())
        if not batches:
            return []
        self.cache.begin_pass(self._pass_ids(batches))
        try:
            return [step_fn(self.cache, b) for b in batches]
        finally:
            self.cache.end_pass()


class CompiledPassStep:
    """ONE-dispatch pass step: embedding gather + dense forward/backward
    + dense optimizer update + embedding-grad accumulation, compiled as a
    single XLA program.

    The eager heter_embedding path dispatches dozens of host ops per
    batch and round-trips the embedding rows host<->device every step —
    on a TPU behind a network tunnel that transfer dominates. Here the
    pass cache's row slab, the grad accumulator, and the dense optimizer
    state all live on device across the whole pass (ps_gpu_wrapper.cc
    keeps them in GPU memory the same way); per-step host work is the
    vectorized id->slot translation plus an int32 upload.

        trainer = HeterPassTrainer(client, table_id=0, lr=0.1)
        step = CompiledPassStep(trainer.cache, deep_model, optimizer,
                                loss_fn)
        trainer.train_from_dataset(dataset, step, passes=1)

    loss_fn(output_tensor, labels_tensor) -> scalar Tensor.
    """

    def __init__(self, cache: DevicePassCache, model, optimizer, loss_fn,
                 table_optimizer=None, table_lr=0.1):
        """table_optimizer: None keeps downpour semantics (grads
        accumulate, merged push at end_pass); "adagrad"/"sgd" runs the
        embedding update ON DEVICE each step (ps_gpu_wrapper's device
        optimizer) — pair with cache.end_pass(assign=True)."""
        self.cache = cache
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.table_optimizer = table_optimizer
        self.table_lr = float(table_lr)
        from ...jit.functional import FunctionalModule

        self._fm = FunctionalModule(model)
        self._opt_state = None
        self._step_idx = 0
        self._jit = None

    def _build(self):
        import jax
        import jax.numpy as jnp

        from ...framework import autograd
        from ...jit.functional import vals_to_tensors

        fm, opt, loss_fn = self._fm, self.optimizer, self.loss_fn

        def pure(train_p, frozen_p, bvals, opt_state, rows, gacc, slots,
                 labels, key, lr):
            def loss_of(tp, rv):
                emb = jnp.take(rv, slots, axis=0)
                flat = emb.reshape((slots.shape[0], -1))
                pv = fm.merge_values(list(tp), list(frozen_p))
                out_vals, new_b = fm.call(pv, list(bvals), key, (flat,),
                                          training=True)
                outs = vals_to_tensors(out_vals)
                with autograd.no_grad():
                    loss_t = loss_fn(outs, vals_to_tensors((labels,))[0])
                return loss_t._value.astype(jnp.float32), new_b

            (loss, new_b), (g_p, g_rows) = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True)(tuple(train_p), rows)
            new_p, new_state = opt.apply_gradients_tree(
                list(train_p), list(g_p), opt_state, lr)
            if self.table_optimizer is None:
                return loss, new_p, new_state, rows, gacc + g_rows, new_b
            # device-side embedding optimizer: the cached rows train
            # per step; end_pass(assign=True) writes values back
            if self.table_optimizer == "adagrad":
                gacc = gacc + g_rows * g_rows
                rows = rows - self.table_lr * g_rows / jnp.sqrt(gacc + 1e-8)
            else:  # sgd
                rows = rows - self.table_lr * g_rows
            return loss, new_p, new_state, rows, gacc, new_b

        self._jit = jax.jit(pure, donate_argnums=(3, 4, 5))

    def __call__(self, cache: DevicePassCache, batch):
        """batch: (ids, labels) numpy arrays. Returns the loss Tensor."""
        import jax.numpy as jnp

        from ...framework.tensor import Tensor

        ids, labels = batch[0], batch[1]
        fm, opt = self._fm, self.optimizer
        train_p, frozen_p = fm.split_values(fm.param_values())
        if self._jit is None:
            self._build()
        if self._opt_state is None:
            self._opt_state = opt.init_state_tree(train_p)
        slots = jnp.asarray(cache.slots(ids))
        lr = jnp.asarray(float(opt.get_lr()) if hasattr(opt, "get_lr")
                         else 0.001, jnp.float32)
        import jax

        self._step_idx += 1  # fresh dropout mask per step
        (loss, new_p, self._opt_state, cache._rows, cache._gacc,
         new_b) = self._jit(
            tuple(train_p), tuple(frozen_p), fm.buffer_values(),
            self._opt_state, cache._rows, cache._gacc, slots,
            jnp.asarray(labels), jax.random.key(self._step_idx), lr)
        # write updated dense params + buffers back into the live model
        ti = 0
        for p, m in zip(fm.params, fm.trainable_mask):
            if m:
                p._value = new_p[ti]
                ti += 1
        fm.bind_buffers(new_b)
        return Tensor(loss, _internal=True)

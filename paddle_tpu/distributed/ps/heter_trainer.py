"""Heter-PS pass trainer: the PSGPUTrainer drive loop over DevicePassCache.

Reference: PSGPUTrainer / HeterXpuTrainer (paddle/fluid/framework/
trainer.h:179,249) and ps_gpu_wrapper.cc BuildGPUTask: each training PASS
bulk-pulls its sparse working set into device memory, every in-pass lookup
is a device gather (no per-batch host-PS hop), and the merged gradients
push back once at pass end (downpour semantics: one optimizer step per
pass per key with the summed gradient).

TPU-native: DevicePassCache holds the rows as one jnp array; lookups fuse
into the jitted step as XLA gathers. heter_embedding() is the drop-in for
distributed_lookup_table inside the step — same Tensor-with-grad surface,
but backward scatter-adds into the device accumulator instead of a host
push per step.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from .heter_cache import DevicePassCache

__all__ = ["HeterPassTrainer", "heter_embedding"]


def heter_embedding(cache, ids):
    """Cache-backed embedding lookup with gradient accumulation.

    Works over either cache tier: the pass-scoped DevicePassCache (rows
    pulled once by begin_pass) or the capacity-bounded HeterCache (LRU/LFU
    with batched faults). Forward: device gather. Backward: device
    scatter-add into the cache's grad accumulator — the host PS sees
    merged pushes at end_pass/flush/eviction, not one per step
    (ps_gpu_wrapper.cc push_sparse-at-EndPass semantics).
    """
    import jax
    import jax.numpy as jnp

    from ...framework import autograd
    from ...framework.tensor import Tensor

    ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
    if isinstance(cache, DevicePassCache):
        slot_idx = cache.slots(ids_np)  # one host translation per batch
        out_val = cache.lookup_slots(jnp.asarray(slot_idx))

        def backward(cot, dim):
            cache._push_slot_grads(slot_idx.reshape(-1),
                                   np.asarray(cot).reshape(-1, dim))
    else:  # HeterCache: faulting lookup; grads keyed by id
        out_val = cache.lookup(ids_np)

        def backward(cot, dim):
            cache.push_grads(ids_np.reshape(-1),
                             np.asarray(cot).reshape(-1, dim))

    out = Tensor(out_val, _internal=True)
    if autograd.is_grad_enabled():
        dim = out_val.shape[-1]

        def vjp_fn(cot):
            backward(cot, dim)
            return []

        node = autograd.GradNode(
            vjp_fn, [],
            [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
            multi_output=False, name="heter_embedding")
        out.stop_gradient = False
        out._grad_node = node
        out._out_index = 0
    return out


class HeterPassTrainer:
    """Drives train_from_dataset with the pass lifecycle of PSGPUTrainer.

    step_fn(cache, batch) runs one mini-batch (typically: heter_embedding
    lookups + dense forward/backward + dense optimizer step); the trainer
    owns BuildGPUTask (working-set union + ONE bulk pull) before the pass
    and the merged push after it.
    """

    def __init__(self, client, table_id: int, lr: float = -1.0,
                 sparse_slots: Sequence[int] = (0,)):
        self.cache = DevicePassCache(client, table_id, lr=lr)
        self.sparse_slots = tuple(sparse_slots)

    def _pass_ids(self, batches):
        return np.concatenate(
            [np.asarray(b[s], np.uint64).reshape(-1)
             for b in batches for s in self.sparse_slots])

    def train_from_dataset(self, dataset, step_fn: Callable, passes: int = 1):
        """One or more passes over `dataset`. Per pass: BuildGPUTask
        (materialize the pass, union its sparse ids, one bulk pull),
        per-batch device-gather steps, EndPass merged push. Returns the
        last pass's step_fn outputs."""
        outs = []
        for _ in range(int(passes)):
            batches = list(dataset.iterate())
            if not batches:
                return outs
            self.cache.begin_pass(self._pass_ids(batches))
            try:
                outs = [step_fn(self.cache, b) for b in batches]
            finally:
                self.cache.end_pass()
        return outs

    def infer_from_dataset(self, dataset, step_fn: Callable):
        """Evaluation twin: pull the working set, run step_fn per batch
        (no grads accumulate -> end_pass pushes nothing)."""
        batches = list(dataset.iterate())
        if not batches:
            return []
        self.cache.begin_pass(self._pass_ids(batches))
        try:
            return [step_fn(self.cache, b) for b in batches]
        finally:
            self.cache.end_pass()

"""Recommendation-scale PS hot path: compiled dense step + async sharded
embedding pipeline (ISSUE 20).

The eager Wide&Deep path (`distributed_lookup_table` per step) dispatches
dozens of host ops and one PS round trip per mini-batch — measured ~3k
examples/s against a compiled-step roofline of ~3.3M for the identical
config (`artifacts/widedeep_aot_probe.json`). This module closes that gap
with the heter-PS recipe the reference fleet ran (dense on accelerator,
sparse on host), rebuilt on this repo's primitives:

* **PsTrainStep** — the dense hot loop as ONE jitted XLA program (the
  `jit.TrainStep` seam: FunctionalModule + optimizer.apply_gradients_tree
  + donated carried state, warm-keyed through `jit/artifact_cache` like
  PR 19): it consumes the pre-gathered embedding rows as a `[pad_rows,
  dim]` device array plus `[batch, slots]` int32 gather indices and emits
  the sparse row-gradients as an OUTPUT (the gather's transpose is a
  scatter-add, so duplicate ids inside a batch accumulate in-trace). No
  per-slot host round trip exists inside the step.

* **PsPipeline** — double-buffered async pull/push: while step *k* runs
  on-chip, a prefetch worker pulls step *k+1*'s unique keys (directly or
  through a `HeterCache`) and a push worker commits step *k−1*'s row
  grads; `FLAGS_ps_pipeline_depth` bounds the in-flight window (depth 1 =
  bit-identical serial reference). Exposed pull/push wait — the part the
  pipeline failed to hide — is measured per step and gated by bench_gate.

* **BusShardedClient / PsShardService** — embedding tables sharded across
  hosts by the splitmix64 key-hash, served by request/reply actors on the
  cross-host `MessageBus` (`fleet_executor.ServiceInterceptor`); pull and
  push payloads are quantized through the PR-8 `int8_block`/`fp8_block`
  blockwise codecs with a client-side error-feedback residual per table
  shard on the push wire. Failure model per PR 4: per-attempt timeout +
  exponential-backoff retry; a shard that exhausts retries is declared
  dead LOUDLY (typed `DeadShardError` naming the shard task/host, ERROR
  event, flight-recorder note) — `FLAGS_ps_degraded_ok` switches to a
  degraded mode that serves zeros for the dead shard's keys and
  drops-and-counts its pushes instead of failing the step.

Wire-byte accounting (`ps_pull_bytes_total{codec=}` /
`ps_push_bytes_total{codec=}`) counts what actually crosses the bus:
quantized payload + per-block fp32 scales + uint64 keys. Push retries are
at-least-once: a reply lost after the server applied the push re-applies
the merged gradient once — acceptable under downpour semantics, flagged
here because it is a real semantic of retried non-idempotent RPCs.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...framework.flags import flag
from ...observability.metrics import get_registry as _get_registry

__all__ = [
    "DeadShardError", "PsShardService", "BusShardedClient",
    "make_sharded_ps", "PsTrainStep", "PsPipeline", "encode_rows",
    "decode_rows", "wire_nbytes",
]

_m_pull_bytes = _get_registry().counter(
    "ps_pull_bytes_total", help="sharded PS pull payload bytes on the wire",
    labels=("codec",))
_m_push_bytes = _get_registry().counter(
    "ps_push_bytes_total", help="sharded PS push payload bytes on the wire",
    labels=("codec",))
_m_degraded = _get_registry().counter(
    "ps_degraded_ops_total",
    help="pull/push ops served degraded because a shard host is dead",
    labels=("shard",))
_m_steps = _get_registry().counter(
    "ps_pipeline_steps_total", help="compiled PS pipeline steps run").bind()

PS_WIRE_CODECS = ("fp32", "int8_block", "fp8_block")


class DeadShardError(RuntimeError):
    """A shard host exhausted its pull/push retries — the PR-4 fail-fast
    path. Carries the shard index and bus task id so a stall names the
    hung host."""

    def __init__(self, msg, shard=None, task_id=None, op=None):
        super().__init__(msg)
        self.shard = shard
        self.task_id = task_id
        self.op = op


# --------------------------------------------------------------------------
# blockwise wire codec (the PR-8 grad_comm transforms, packed for the TLV
# bus: int8 payloads travel as np.int8, fp8 as the uint8 bitcast)
# --------------------------------------------------------------------------

def _codec_block():
    return int(flag("FLAGS_ps_wire_block", 1024))


def _fp8_np_dtype():
    import jax.numpy as jnp

    fp8 = getattr(jnp, "float8_e4m3fn", None)
    if fp8 is None:
        raise RuntimeError("fp8_block needs jnp.float8_e4m3fn "
                           "(jax>=0.4 with ml_dtypes)")
    return np.dtype(fp8)


def _np_blocks(flat: np.ndarray, bs: int) -> np.ndarray:
    """(n_blocks, bs) zero-padded view — grad_comm._as_blocks in numpy."""
    n = flat.size
    nb = -(-n // bs)
    pad = nb * bs - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(nb, bs)


def encode_rows(rows: np.ndarray, codec: str, block: Optional[int] = None):
    """[n, dim] f32 -> (wire payload dict, EF residual [n, dim] or None).

    The quantization math is grad_comm's blockwise codec mirrored
    IEEE-op-for-op in numpy (abs-max/block, scale = max(absmax,1e-12)/QMAX,
    round-half-to-even to [-127,127] int8 / cast to float8_e4m3fn) — the
    parity test pins bit-equality against block_absmax/block_scales/
    block_encode, so the bits on this wire are the bits every PR-8
    guarantee was proven against. numpy on purpose: this runs on the
    pull/push worker threads for a DIFFERENT row count every step, and the
    jnp pair would mint one compiled program per distinct numel (measured:
    the int8 pipeline ran slower than eager before this)."""
    rows = np.asarray(rows, np.float32)
    if codec == "fp32":
        return {"codec": "fp32", "rows": rows}, None
    if codec not in PS_WIRE_CODECS:
        raise ValueError(f"unknown PS wire codec {codec!r}; "
                         f"one of {PS_WIRE_CODECS}")
    from ..grad_comm import _QMAX

    bs = int(block or _codec_block())
    numel = rows.size
    blocks = _np_blocks(rows.reshape(-1), bs)
    absmax = np.abs(blocks).max(axis=1)
    scales = (np.maximum(absmax, 1e-12) / _QMAX[codec]).astype(np.float32)
    q = blocks / scales[:, None]
    if codec == "int8_block":
        qv = np.clip(np.round(q), -127, 127).astype(np.int8)
        wire = qv
    else:  # fp8_block: the exact fp8 values, bitcast to uint8 for the TLV.
        # f16 intermediate on purpose: XLA lowers f32->f8E4M3FN through
        # f16, and bit-parity with the jnp codec (the parity test) needs
        # the same double rounding; q is <= QMAX=448, far from f16 range.
        qv = q.astype(np.float16).astype(_fp8_np_dtype())
        wire = qv.view(np.uint8)
    # Only the first ``numel`` quantized elements travel — block padding
    # dequantizes to zeros, so the receiver reconstructs it for free.
    # (Measured: at block=1024 the padding alone pushed the int8 wire
    # from 0.296x to 0.304x of fp32.)
    payload = {"codec": codec, "q": wire.reshape(-1)[:numel], "s": scales,
               "shape": list(rows.shape), "block": bs}
    deq = (qv.astype(np.float32) * scales[:, None]).reshape(-1)[:numel]
    resid = (rows.reshape(-1) - deq).reshape(rows.shape)
    return payload, resid


def decode_rows(payload) -> np.ndarray:
    """Inverse of encode_rows, pure numpy (runs on shard-host threads)."""
    if payload["codec"] == "fp32":
        return np.asarray(payload["rows"], np.float32)
    n, dim = payload["shape"]
    q = np.asarray(payload["q"]).reshape(-1)
    if payload["codec"] == "fp8_block":
        q = q.view(_fp8_np_dtype())
    scales = np.asarray(payload["s"], np.float32)
    bs = int(payload["block"])
    pad = len(scales) * bs - q.size   # wire is truncated to numel
    if pad:
        q = np.concatenate([q, np.zeros(pad, q.dtype)])
    vals = q.astype(np.float32).reshape(len(scales), bs) * scales[:, None]
    return vals.reshape(-1)[:n * dim].reshape(n, dim)


def wire_nbytes(payload, keys: Optional[np.ndarray] = None) -> int:
    """Bytes this payload puts on the bus: quantized rows (or fp32 rows) +
    per-block scales + the uint64 key vector riding with it."""
    if payload["codec"] == "fp32":
        n = int(np.asarray(payload["rows"]).nbytes)
    else:
        n = int(payload["q"].nbytes + payload["s"].nbytes)
    if keys is not None:
        n += int(np.asarray(keys).nbytes)
    return n


# --------------------------------------------------------------------------
# sharded transport over the MessageBus
# --------------------------------------------------------------------------

def _shard_of(keys: np.ndarray, n: int) -> np.ndarray:
    """splitmix64-style mix -> shard index (the PsClient._route hash, so
    bus sharding and TCP sharding agree on key placement)."""
    keys = np.asarray(keys, np.uint64).reshape(-1)
    if n == 1:
        return np.zeros(keys.shape, np.int64)
    with np.errstate(over="ignore"):
        h = keys * np.uint64(0x9E3779B97F4A7C15)
        h ^= h >> np.uint64(30)
        h = h * np.uint64(0xBF58476D1CE4E5B9)
        h ^= h >> np.uint64(31)
    return (h % np.uint64(n)).astype(np.int64)


class PsShardService:
    """One shard host: a table backend behind a bus request/reply actor.

    The backend is any PS client duck (LocalPs by default) owning this
    shard's slice of every table. Pull requests name the codec they want
    the rows quantized with; push requests arrive quantized and are
    dequantized here before the backend's merged-gradient apply."""

    def __init__(self, bus, task_id: int, backend=None, name: str = ""):
        from .. import fleet_executor as fx
        from . import LocalPs

        self.backend = backend if backend is not None else LocalPs()
        self.task_id = int(task_id)
        self.name = name or f"shard@task{task_id}"
        self._node = fx.TaskNode(task_id=self.task_id, role="compute")
        self.interceptor = fx.ServiceInterceptor(self._node, bus, {
            "create_table": self._create_table,
            "pull": self._pull,
            "push": self._push,
            "assign": self._assign,
            "add": self._add,
            "table_size": self._table_size,
        })
        self.interceptor.start()

    def _create_table(self, table_id, dim, kw=None):
        self.backend.create_table(int(table_id), int(dim), **(kw or {}))
        return True

    def _pull(self, table_id, keys, codec="fp32"):
        rows = np.asarray(
            self.backend.pull(int(table_id), np.asarray(keys, np.uint64)),
            np.float32)
        payload, _ = encode_rows(rows, codec)
        return payload

    def _push(self, table_id, keys, payload, lr=-1.0):
        keys = np.asarray(keys, np.uint64)
        grads = decode_rows(payload)
        self.backend.push(int(table_id), keys, grads, lr=float(lr))
        return True

    def _assign(self, table_id, keys, values):
        self.backend.assign(int(table_id), np.asarray(keys, np.uint64),
                            np.asarray(values, np.float32))
        return True

    def _add(self, table_id, keys, deltas):
        self.backend.add(int(table_id), np.asarray(keys, np.uint64),
                         np.asarray(deltas, np.float32))
        return True

    def _table_size(self, table_id):
        return int(self.backend.table_size(int(table_id)))

    def stop(self):
        self.interceptor.stop()
        self.interceptor.join(timeout=5)


class BusShardedClient:
    """Key-hash sharded PS client over the MessageBus — the same
    pull/push/assign/add duck as LocalPs/PsClient, so `DevicePassCache`,
    `HeterCache`, and the communicators sit on it unchanged.

    Wire: pulls ask each owning shard for rows quantized with
    `FLAGS_ps_wire_codec`; pushes quantize per shard with an
    error-feedback residual kept per (table, shard) keyed by row id (the
    PR-8 EF discipline — what the wire rounded away this push is added
    back before the next quantize of the same rows), so the quantized
    push wire converges to the fp32-wire fixpoint instead of biasing it.
    The residual store grows with the touched vocabulary of this worker,
    the same bound as the tables themselves.

    Failure model (PR 4): each RPC gets `FLAGS_ps_pull_timeout_s` per
    attempt and `FLAGS_ps_pull_retries` retries with exponential backoff.
    Exhaustion marks the shard DEAD and either raises `DeadShardError`
    (default) or, under `FLAGS_ps_degraded_ok`, serves the shard's keys
    degraded (zero rows on pull, dropped-and-counted pushes) after one
    ERROR event naming the host."""

    def __init__(self, bus, shard_tasks: Sequence[int], client_task: int,
                 codec: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 degraded_ok: Optional[bool] = None,
                 shard_names: Optional[Sequence[str]] = None):
        from .. import fleet_executor as fx

        self.bus = bus
        self.shard_tasks = [int(t) for t in shard_tasks]
        self.codec = codec if codec is not None \
            else str(flag("FLAGS_ps_wire_codec", "fp32"))
        if self.codec not in PS_WIRE_CODECS:
            raise ValueError(f"FLAGS_ps_wire_codec={self.codec!r}; "
                             f"one of {PS_WIRE_CODECS}")
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else flag("FLAGS_ps_pull_timeout_s", 10.0))
        self.retries = int(retries if retries is not None
                           else flag("FLAGS_ps_pull_retries", 2))
        self.degraded_ok = bool(degraded_ok if degraded_ok is not None
                                else flag("FLAGS_ps_degraded_ok", False))
        self.shard_names = list(shard_names or
                                [f"task{t}" for t in self.shard_tasks])
        self._rpc = fx.BusRpcClient(bus, int(client_task))
        self._dims: Dict[int, int] = {}
        self._resid: Dict[tuple, Dict[int, np.ndarray]] = {}
        self._resid_lock = threading.Lock()
        self._dead: set = set()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, len(self.shard_tasks)))
        self.pull_bytes = 0   # plain mirrors of the wire counters, for
        self.push_bytes = 0   # tests/bench that want deltas without /metrics
        self.dropped_pushes = 0

    # ---- failure model -------------------------------------------------
    def _declare_dead(self, shard: int, op: str, err):
        from ...observability import get_event_log
        from ...observability.flight_recorder import get_flight_recorder

        first = shard not in self._dead
        self._dead.add(shard)
        if first:
            get_event_log().error(
                "ps_shard_dead", shard=int(shard),
                task_id=self.shard_tasks[shard],
                host=self.shard_names[shard], op=op, err=str(err))
            get_flight_recorder().note(
                "ps", "shard_dead", shard=int(shard),
                host=self.shard_names[shard], op=op)
        if not self.degraded_ok:
            raise DeadShardError(
                f"PS shard {shard} ({self.shard_names[shard]}, bus task "
                f"{self.shard_tasks[shard]}) dead after "
                f"{self.retries + 1} {op} attempts x {self.timeout_s}s: "
                f"{err}", shard=shard, task_id=self.shard_tasks[shard],
                op=op)

    def _call_shard(self, shard: int, op: str, **kw):
        """One RPC under the timeout/retry/backoff policy. Returns None
        when the shard is dead and degraded mode is on (callers fill in
        the degraded behavior)."""
        if shard in self._dead:
            _m_degraded.labels(shard=str(shard)).inc()
            if self.degraded_ok:
                return None
            raise DeadShardError(
                f"PS shard {shard} ({self.shard_names[shard]}) is dead",
                shard=shard, task_id=self.shard_tasks[shard], op=op)
        delay = 0.05
        last = None
        for _attempt in range(self.retries + 1):
            try:
                return self._rpc.call(self.shard_tasks[shard], op,
                                      timeout=self.timeout_s, **kw)
            except TimeoutError as e:
                last = e
                time.sleep(delay)
                delay *= 2
        self._declare_dead(shard, op, last)  # raises unless degraded_ok
        _m_degraded.labels(shard=str(shard)).inc()
        return None

    # ---- table admin ---------------------------------------------------
    def create_table(self, table_id, dim, **kw):
        self._dims[int(table_id)] = int(dim)
        for s in range(len(self.shard_tasks)):
            self._call_shard(s, "create_table", table_id=int(table_id),
                             dim=int(dim), kw=kw)

    def table_size(self, table_id):
        total = 0
        for s in range(len(self.shard_tasks)):
            n = self._call_shard(s, "table_size", table_id=int(table_id))
            total += int(n or 0)
        return total

    # ---- data plane ----------------------------------------------------
    def _route(self, keys):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        shard = _shard_of(keys, len(self.shard_tasks))
        out = []
        for s in range(len(self.shard_tasks)):
            idx = np.nonzero(shard == s)[0]
            if idx.size:
                out.append((s, idx, keys[idx]))
        return out

    def pull(self, table_id, keys, create_if_missing=True):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        dim = self._dims.get(int(table_id))
        out = None
        futs = [(s, idx, sk,
                 self._pool.submit(self._call_shard, s, "pull",
                                   table_id=int(table_id), keys=sk,
                                   codec=self.codec))
                for s, idx, sk in self._route(keys)]
        for s, idx, sk, fut in futs:
            payload = fut.result()
            if payload is None:          # dead shard, degraded: zero rows
                if dim is None:
                    raise DeadShardError(
                        f"degraded pull needs a known dim for table "
                        f"{table_id}; create_table through this client",
                        shard=s, task_id=self.shard_tasks[s], op="pull")
                rows = np.zeros((idx.size, dim), np.float32)
            else:
                nb = wire_nbytes(payload, sk)
                self.pull_bytes += nb
                _m_pull_bytes.labels(codec=self.codec).inc(nb)
                rows = decode_rows(payload)
            if out is None:
                out = np.empty((keys.size, rows.shape[1]), np.float32)
            out[idx] = rows
        return out if out is not None \
            else np.zeros((0, dim or 0), np.float32)

    def _push_one(self, table_id, s, sk, grads, lr):
        """Quantize one shard's merged grads (EF residual folded in and
        carried per (table, shard)) and push."""
        g = np.asarray(grads, np.float32)
        rkey = (int(table_id), int(s))
        if self.codec != "fp32":
            with self._resid_lock:
                res = self._resid.setdefault(rkey, {})
                for i, k in enumerate(sk.tolist()):
                    r = res.get(int(k))
                    if r is not None:
                        g = g.copy() if g is grads else g
                        g[i] = g[i] + r
        payload, new_res = encode_rows(g, self.codec)
        if new_res is not None:
            with self._resid_lock:
                res = self._resid.setdefault(rkey, {})
                for i, k in enumerate(sk.tolist()):
                    res[int(k)] = new_res[i]
        ok = self._call_shard(s, "push", table_id=int(table_id), keys=sk,
                              payload=payload, lr=float(lr))
        if ok is None:                    # dead shard, degraded: drop loud
            self.dropped_pushes += len(sk)
            return
        nb = wire_nbytes(payload, sk)
        self.push_bytes += nb
        _m_push_bytes.labels(codec=self.codec).inc(nb)

    def push(self, table_id, keys, grads, lr=-1.0):
        from .communicator import merge_sparse

        keys = np.asarray(keys, np.uint64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        keys, grads = merge_sparse(keys, grads)  # duplicate ids SUM here
        futs = [self._pool.submit(self._push_one, table_id, s, sk,
                                  grads[idx], lr)
                for s, idx, sk in self._route(keys)]
        for f in futs:
            f.result()

    def assign(self, table_id, keys, values):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        values = np.asarray(values, np.float32).reshape(keys.size, -1)
        for s, idx, sk in self._route(keys):
            self._call_shard(s, "assign", table_id=int(table_id), keys=sk,
                             values=values[idx])

    def add(self, table_id, keys, deltas):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(keys.size, -1)
        for s, idx, sk in self._route(keys):
            self._call_shard(s, "add", table_id=int(table_id), keys=sk,
                             deltas=deltas[idx])

    def close(self):
        self._pool.shutdown(wait=False)
        self._rpc.close()


def make_sharded_ps(n_shards: Optional[int] = None, bus=None,
                    base_task: int = 9000, codec: Optional[str] = None,
                    **client_kw):
    """Build an in-process sharded PS: one MessageBus, `n_shards`
    PsShardService actors (LocalPs backends), one BusShardedClient.
    Returns (client, services, bus). Cross-host deployments construct the
    same pieces per rank and wire bus.listen()/connect() instead."""
    from .. import fleet_executor as fx

    n = int(n_shards if n_shards is not None else flag("FLAGS_ps_shards", 1))
    bus = bus or fx.MessageBus(rank=0)
    services = [PsShardService(bus, base_task + i, name=f"shard{i}")
                for i in range(n)]
    client = BusShardedClient(
        bus, [s.task_id for s in services], client_task=base_task + n,
        codec=codec, shard_names=[s.name for s in services], **client_kw)
    return client, services, bus


# --------------------------------------------------------------------------
# the compiled dense step
# --------------------------------------------------------------------------

_step_warm: Dict[str, object] = {}   # process-global degraded artifact tier
_step_warm_lock = threading.Lock()


class PsTrainStep:
    """ONE-dispatch dense Wide&Deep step over pre-gathered rows.

    pure(train_p, frozen_p, bvals, opt_state, rows, slots, labels, key,
    lr) -> (loss, new_p, new_state, row_grads, new_b): embedding gather +
    dense forward/backward + dense optimizer update, with the sparse
    row-gradients EMITTED as an output for the pipeline's async push
    (CompiledPassStep keeps them in a device accumulator instead — that
    is the pass-scoped variant; this is the streaming one). jax's gather
    transpose is a scatter-add, so duplicate ids within a batch sum into
    their shared row — the classic PS last-write-win bug cannot happen
    in-trace.

    Shape contract: rows [pad_rows, dim] f32, slots [batch, n_slots]
    int32, labels [batch] f32 — all fixed, so one compiled program serves
    the whole run. The compiled fn is registered in the PR-19 artifact
    tier under cache_key((model fingerprint, geometry), ...): in-process
    re-instantiations warm-start, and where jax.export exists the disk
    tier persists across processes (`FLAGS_artifact_cache_dir`)."""

    def __init__(self, model, optimizer, loss_fn, dim: int, pad_rows: int):
        from ...jit.functional import FunctionalModule

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.dim = int(dim)
        self.pad_rows = int(pad_rows)
        self._fm = FunctionalModule(model)
        self._opt_state = None
        self._step_idx = 0
        self._jit = None
        self.cache_hit = False   # warm-map hit at build time (tests)

    def _fingerprint(self, batch: int, n_slots: int) -> str:
        shapes = ",".join(f"{tuple(p._value.shape)}" for p in self._fm.params)
        return (f"ps_step:{type(self.model).__name__}:"
                f"{type(self.optimizer).__name__}:{shapes}")

    def _build(self, batch: int, n_slots: int):
        import jax

        from ...jit.artifact_cache import cache_key

        key = cache_key(self._fingerprint(batch, n_slots),
                        (self.pad_rows, self.dim, batch, n_slots),
                        "float32")
        with _step_warm_lock:
            hit = _step_warm.get(key)
        if hit is not None:
            self._jit = hit
            self.cache_hit = True
            return
        fn = jax.jit(self._pure(), donate_argnums=(3, 4))
        self._register_artifact(key, fn)
        self._jit = fn

    def _register_artifact(self, key: str, fn):
        """PR-19 artifact tier: always the in-process warm map; the disk
        tier additionally persists where jax.export exists (probed — its
        absence is the documented degraded mode)."""
        with _step_warm_lock:
            _step_warm[key] = fn
        root = flag("FLAGS_artifact_cache_dir", "")
        if not root:
            return
        from ...jit.artifact_cache import ArtifactCache, export_supported

        if export_supported():
            ArtifactCache(root).store(key, fn)

    def _pure(self):
        import jax
        import jax.numpy as jnp

        from ...framework import autograd
        from ...jit.functional import vals_to_tensors

        fm, opt, loss_fn = self._fm, self.optimizer, self.loss_fn

        def pure(train_p, frozen_p, bvals, opt_state, rows, slots, labels,
                 key, lr):
            def loss_of(tp, rv):
                emb = jnp.take(rv, slots, axis=0)
                flat = emb.reshape((slots.shape[0], -1))
                pv = fm.merge_values(list(tp), list(frozen_p))
                out_vals, new_b = fm.call(pv, list(bvals), key, (flat,),
                                          training=True)
                outs = vals_to_tensors(out_vals)
                with autograd.no_grad():
                    loss_t = loss_fn(outs, vals_to_tensors((labels,))[0])
                return loss_t._value.astype(jnp.float32), new_b

            (loss, new_b), (g_p, g_rows) = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True)(tuple(train_p), rows)
            new_p, new_state = opt.apply_gradients_tree(
                list(train_p), list(g_p), opt_state, lr)
            return loss, new_p, new_state, g_rows, new_b

        return pure

    def __call__(self, rows, slots, labels):
        """rows: [pad_rows, dim] device array (donated — do not reuse);
        slots: [batch, n_slots] int32; labels: [batch] f32. Returns
        (loss, row_grads) as DEVICE values — no host sync here; the
        pipeline's push worker syncs row_grads off the main thread."""
        import jax
        import jax.numpy as jnp

        fm, opt = self._fm, self.optimizer
        slots = jnp.asarray(slots, jnp.int32)
        if self._jit is None:
            self._build(int(slots.shape[0]), int(slots.shape[1]))
        train_p, frozen_p = fm.split_values(fm.param_values())
        if self._opt_state is None:
            self._opt_state = opt.init_state_tree(train_p)
        lr = jnp.asarray(float(opt.get_lr()) if hasattr(opt, "get_lr")
                         else 0.001, jnp.float32)
        self._step_idx += 1
        (loss, new_p, self._opt_state, g_rows, new_b) = self._jit(
            tuple(train_p), tuple(frozen_p), fm.buffer_values(),
            self._opt_state, rows, slots, jnp.asarray(labels),
            jax.random.key(self._step_idx), lr)
        ti = 0
        for p, m in zip(fm.params, fm.trainable_mask):
            if m:
                p._value = new_p[ti]
                ti += 1
        fm.bind_buffers(new_b)
        _m_steps.inc()
        return loss, g_rows


# --------------------------------------------------------------------------
# the double-buffered driver
# --------------------------------------------------------------------------

class PsPipeline:
    """Async pull/push pipeline around a PsTrainStep.

    Timing diagram at depth 2 (one box per worker thread):

        pull worker : [pull 0][pull 1 ][pull 2 ]...
        main (chip) :         [step 0 ][step 1 ][step 2 ]...
        push worker :                  [push 0 ][push 1 ]...

    While step k computes, pull k+1 prefetches and push k-1 commits; the
    main thread only ever blocks on (a) pull k's future if the prefetch
    failed to hide it (measured: exposed_pull_ms), and (b) the push of
    step k-depth if the wire fell behind (exposed_push_ms). depth 1
    degenerates to pull -> step -> push, bit-identical to the serial
    reference — the parity anchor the tests pin.

    Rows source: `client` directly (every step pulls its unique keys), or
    through a `HeterCache` (`cache=`) for admission + LRU eviction +
    coalesced write-back — the sharded/quantized wire then only sees
    misses and evictions. Tracing: each run() is one trace with
    pull_launch / pull_wait / step / push_commit spans per step (the PR-18
    shape); a pull that dies names the hung shard host in its span and in
    the DeadShardError."""

    def __init__(self, client, table_id: int, step: PsTrainStep,
                 depth: Optional[int] = None, lr_sparse: float = 0.1,
                 cache=None, name: str = "ps_pass"):
        self.client = client
        self.table_id = int(table_id)
        self.step = step
        self.depth = max(1, int(depth if depth is not None
                                else flag("FLAGS_ps_pipeline_depth", 2)))
        self.lr_sparse = float(lr_sparse)
        self.cache = cache
        self.name = name
        self._pull_pool = ThreadPoolExecutor(max_workers=1)
        self._push_pool = ThreadPoolExecutor(max_workers=1)

    # ---- worker jobs ---------------------------------------------------
    def _pull_job(self, ids: np.ndarray):
        import jax.numpy as jnp

        uniq, inv = np.unique(
            np.asarray(ids, np.uint64).reshape(-1), return_inverse=True)
        if uniq.size > self.step.pad_rows:
            raise ValueError(
                f"batch touches {uniq.size} unique ids > pad_rows="
                f"{self.step.pad_rows}; raise pad_rows")
        if self.cache is not None:
            rows = self.cache.lookup(uniq)           # device [u, dim]
            pad = self.step.pad_rows - int(rows.shape[0])
            if pad:
                rows = jnp.concatenate(
                    [rows, jnp.zeros((pad, rows.shape[1]), rows.dtype)])
        else:
            rows_np = np.asarray(
                self.client.pull(self.table_id, uniq), np.float32)
            if rows_np.shape[0] < self.step.pad_rows:
                rows_np = np.pad(rows_np, ((0, self.step.pad_rows
                                            - rows_np.shape[0]), (0, 0)))
            rows = jnp.asarray(rows_np)
        slots = inv.astype(np.int32).reshape(np.shape(ids))
        return rows, uniq, slots

    def _push_job(self, ctx, k: int, uniq: np.ndarray, g_rows):
        from ...observability.tracing import get_tracer

        t0 = time.monotonic()
        g = np.asarray(g_rows)[:uniq.size]   # device->host sync, off-main
        nz = np.any(g != 0, axis=1)
        if nz.any():
            if self.cache is not None:
                self.cache.push_grads(uniq[nz], g[nz])
            else:
                self.client.push(self.table_id, uniq[nz], g[nz],
                                 lr=self.lr_sparse)
        get_tracer().record_span(ctx, "push_commit", t_start=t0, step=k,
                                 buf=k % self.depth, n_rows=int(nz.sum()))

    # ---- the drive loop ------------------------------------------------
    def run(self, batches) -> dict:
        """batches: sequence of (ids [batch, slots] uint64, labels
        [batch] f32). Returns throughput/latency stats; losses[] carries
        the per-step loss curve for convergence checks."""
        import jax

        from ...observability.tracing import get_tracer

        batches = list(batches)
        n = len(batches)
        if n == 0:
            return {"steps": 0, "examples_per_s": 0.0, "losses": []}
        tracer = get_tracer()
        ctx = tracer.start_trace(self.name, depth=self.depth,
                                 steps=n, codec=getattr(
                                     self.client, "codec", "local"))
        look = self.depth - 1
        pulls: Dict[int, object] = {}
        pushes: deque = deque()   # (k, future)
        losses: List[float] = []
        exposed_pull = exposed_push = step_s = 0.0

        def launch_pull(i):
            if i < n:
                t0 = time.monotonic()
                pulls[i] = self._pull_pool.submit(self._pull_job,
                                                  batches[i][0])
                tracer.record_span(ctx, "pull_launch", t_start=t0, step=i,
                                   buf=i % self.depth)

        t_run = time.perf_counter()
        for i in range(min(look + 1, n)):
            launch_pull(i)
        try:
            for k in range(n):
                # bound the push window: step k must not outrun push k-depth
                while len(pushes) >= self.depth:
                    pk, fut = pushes.popleft()
                    t0 = time.monotonic()
                    fut.result()
                    exposed_push += time.monotonic() - t0
                t0 = time.monotonic()
                try:
                    rows, uniq, slots = pulls.pop(k).result()
                except DeadShardError as e:
                    tracer.record_span(ctx, "pull_wait", t_start=t0, step=k,
                                       error="dead_shard", shard=e.shard,
                                       task_id=e.task_id)
                    raise
                wait = time.monotonic() - t0
                exposed_pull += wait
                tracer.record_span(ctx, "pull_wait", t_start=t0, step=k,
                                   buf=k % self.depth, n_uniq=int(uniq.size))
                if self.depth > 1:       # prefetch while step k computes
                    launch_pull(k + look + 1)
                t0 = time.monotonic()
                loss, g_rows = self.step(rows, slots, batches[k][1])
                loss = jax.block_until_ready(loss)
                step_s += time.monotonic() - t0
                tracer.record_span(ctx, "step", t_start=t0, step=k,
                                   buf=k % self.depth)
                losses.append(float(loss))
                pushes.append((k, self._push_pool.submit(
                    self._push_job, ctx, k, uniq, g_rows)))
                if self.depth == 1:      # serial mode: commit before next pull
                    t0 = time.monotonic()
                    pushes.popleft()[1].result()
                    exposed_push += time.monotonic() - t0
                    launch_pull(k + 1)
        finally:
            while pushes:
                pushes.popleft()[1].result()
            if self.cache is not None:
                self.cache.flush()
        wall = time.perf_counter() - t_run
        batch = int(np.shape(batches[0][0])[0])
        return {
            "steps": n, "wall_s": round(wall, 4),
            "examples_per_s": round(n * batch / wall, 1),
            "exposed_pull_ms": round(1000 * exposed_pull / n, 4),
            "exposed_push_ms": round(1000 * exposed_push / n, 4),
            "step_ms": round(1000 * step_s / n, 4),
            "losses": losses,
        }

    def close(self):
        self._pull_pool.shutdown(wait=False)
        self._push_pool.shutdown(wait=False)

"""Heterogeneous-PS device cache: pass-scoped embeddings on the TPU.

Reference: paddle/fluid/framework/fleet/heter_ps/ (heter_comm.h,
ps_gpu_wrapper.cc BuildGPUTask/pull_box path): before each training pass
the working set of sparse rows is pulled from the host PS into device
memory, lookups during the pass are pure device gathers, and the merged
gradients push back once at pass end.

TPU-native: the cached rows live as ONE jnp array (device-resident, so
in-pass lookups are XLA gathers that fuse into the step — no host
callback per batch, the problem the per-step `distributed_lookup_table`
host hop has); the id→slot map is host-side numpy. Gradient merge runs as
a device scatter-add and hits the PS once per pass — the reference's
downpour per-pass merged-update semantics (one optimizer step per pass
per key with the summed gradient).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["DevicePassCache"]


class DevicePassCache:
    def __init__(self, client, table_id: int, lr: float = -1.0):
        self.client = client
        self.table_id = int(table_id)
        self.lr = float(lr)
        self._slot_of: dict = {}
        self._keys: Optional[np.ndarray] = None
        self._rows = None     # [n_keys, dim] device array
        self._gacc = None     # [n_keys, dim] device grad accumulator
        self.pulls = 0        # host-PS round-trips (observability/tests)
        self.pushes = 0

    # -- pass lifecycle ------------------------------------------------------
    def begin_pass(self, all_ids):
        """Pull the pass's unique working set into device memory
        (BuildGPUTask: one bulk pull, not per-batch hops)."""
        import jax.numpy as jnp

        keys = np.unique(np.asarray(all_ids, np.uint64).reshape(-1))
        rows = self.client.pull(self.table_id, keys)
        self.pulls += 1
        self._keys = keys
        self._slot_of = {int(k): i for i, k in enumerate(keys.tolist())}
        self._rows = jnp.asarray(rows)
        self._gacc = jnp.zeros_like(self._rows)
        return self

    def slots(self, ids) -> np.ndarray:
        """Host-side id→slot translation (vectorized binary search over the
        sorted working set — the hot path must not loop in Python); the
        returned indices drive pure device gathers/scatters in jitted code."""
        if self._keys is None:
            raise RuntimeError("begin_pass() first")
        flat = np.asarray(ids, np.uint64).reshape(-1)
        idx = np.searchsorted(self._keys, flat)
        idx_c = np.minimum(idx, self._keys.size - 1)
        bad = self._keys[idx_c] != flat
        if bad.any():
            raise KeyError(
                f"id {int(flat[bad][0])} not in this pass's working set; "
                f"include it in begin_pass(all_ids)")
        return idx.astype(np.int32).reshape(np.shape(ids))

    def lookup(self, ids):
        """[*ids.shape, dim] device gather. For jitted steps, pre-translate
        once with slots() and use lookup_slots() inside the jit."""
        import jax.numpy as jnp

        return jnp.take(self._rows, jnp.asarray(self.slots(ids)), axis=0)

    def lookup_slots(self, slot_idx):
        import jax.numpy as jnp

        return jnp.take(self._rows, slot_idx, axis=0)

    def push_grads(self, ids, grads):
        """Accumulate gradients on device (heter_comm merge_grad)."""
        slot_idx = self.slots(ids).reshape(-1)
        self._push_slot_grads(slot_idx, grads)

    def _push_slot_grads(self, slot_idx, grads):
        import jax.numpy as jnp

        g = jnp.asarray(grads).reshape(len(slot_idx), -1)
        self._gacc = self._gacc.at[jnp.asarray(slot_idx)].add(g)

    def end_pass(self):
        """One merged push back to the host PS (ps_gpu_wrapper push_sparse
        at pass end); clears the cache."""
        if self._keys is None:
            return
        g = np.asarray(self._gacc)
        nz = np.any(g != 0, axis=1)
        if nz.any():
            self.client.push(self.table_id, self._keys[nz], g[nz],
                             lr=self.lr)
            self.pushes += 1
        self._keys = None
        self._slot_of = {}
        self._rows = self._gacc = None

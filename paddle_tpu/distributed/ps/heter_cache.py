"""Heterogeneous-PS device cache: pass-scoped embeddings on the TPU.

Reference: paddle/fluid/framework/fleet/heter_ps/ (heter_comm.h,
ps_gpu_wrapper.cc BuildGPUTask/pull_box path): before each training pass
the working set of sparse rows is pulled from the host PS into device
memory, lookups during the pass are pure device gathers, and the merged
gradients push back once at pass end.

TPU-native: the cached rows live as ONE jnp array (device-resident, so
in-pass lookups are XLA gathers that fuse into the step — no host
callback per batch, the problem the per-step `distributed_lookup_table`
host hop has); the id→slot map is host-side numpy. Gradient merge runs as
a device scatter-add and hits the PS once per pass — the reference's
downpour per-pass merged-update semantics (one optimizer step per pass
per key with the summed gradient).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ...observability.metrics import get_registry as _get_registry

__all__ = ["DevicePassCache", "HeterCache"]

_m_cache_hits = _get_registry().counter(
    "ps_cache_hits_total", help="device embedding-cache lookup hits",
    labels=("table",))
_m_cache_misses = _get_registry().counter(
    "ps_cache_misses_total", help="device embedding-cache lookup misses",
    labels=("table",))


def _pow2_pad(idx, fill) -> np.ndarray:
    """Pad an index vector to the next power-of-two length with `fill`.

    Every install/evict/push touches a DIFFERENT number of rows, and a
    scatter/gather whose index length changes is a fresh XLA compile —
    under skewed traffic the cache spent more time compiling than
    training (ISSUE 20 bench). Bucketing the length keeps the compiled-
    program count logarithmic; padded scatter entries point out of bounds
    and are dropped (mode="drop"), padded gather entries read row `fill`
    and are sliced off host-side."""
    n = int(len(idx))
    b = 1
    while b < n:
        b *= 2
    out = np.full(b, fill, np.int32)
    out[:n] = idx
    return out


class DevicePassCache:
    def __init__(self, client, table_id: int, lr: float = -1.0):
        self.client = client
        self.table_id = int(table_id)
        self.lr = float(lr)
        self._slot_of: dict = {}
        self._keys: Optional[np.ndarray] = None
        self._rows = None     # [n_keys, dim] device array
        self._gacc = None     # [n_keys, dim] device grad accumulator
        self.pulls = 0        # host-PS round-trips (observability/tests)
        self.pushes = 0

    # -- pass lifecycle ------------------------------------------------------
    def begin_pass(self, all_ids, pad_to=None):
        """Pull the pass's unique working set into device memory
        (BuildGPUTask: one bulk pull, not per-batch hops). `pad_to` pads
        the device slab to a fixed row count so a jitted step keeps ONE
        compiled program across passes whose working sets differ in
        size (shape stability is the TPU contract)."""
        import jax.numpy as jnp

        keys = np.unique(np.asarray(all_ids, np.uint64).reshape(-1))
        rows = np.asarray(self.client.pull(self.table_id, keys))
        self.pulls += 1
        self._n_real = len(keys)
        if pad_to is not None and pad_to > len(keys):
            rows = np.pad(rows, ((0, pad_to - len(keys)), (0, 0)))
        self._keys = keys
        self._slot_of = {int(k): i for i, k in enumerate(keys.tolist())}
        self._rows = jnp.asarray(rows)
        self._gacc = jnp.zeros_like(self._rows)
        return self

    def slots(self, ids) -> np.ndarray:
        """Host-side id→slot translation (vectorized binary search over the
        sorted working set — the hot path must not loop in Python); the
        returned indices drive pure device gathers/scatters in jitted code."""
        if self._keys is None:
            raise RuntimeError("begin_pass() first")
        flat = np.asarray(ids, np.uint64).reshape(-1)
        idx = np.searchsorted(self._keys, flat)
        idx_c = np.minimum(idx, self._keys.size - 1)
        bad = self._keys[idx_c] != flat
        if bad.any():
            raise KeyError(
                f"id {int(flat[bad][0])} not in this pass's working set; "
                f"include it in begin_pass(all_ids)")
        return idx.astype(np.int32).reshape(np.shape(ids))

    def lookup(self, ids):
        """[*ids.shape, dim] device gather. For jitted steps, pre-translate
        once with slots() and use lookup_slots() inside the jit."""
        import jax.numpy as jnp

        return jnp.take(self._rows, jnp.asarray(self.slots(ids)), axis=0)

    def lookup_slots(self, slot_idx):
        import jax.numpy as jnp

        return jnp.take(self._rows, slot_idx, axis=0)

    def push_grads(self, ids, grads):
        """Accumulate gradients on device (heter_comm merge_grad)."""
        slot_idx = self.slots(ids).reshape(-1)
        self._push_slot_grads(slot_idx, grads)

    def _push_slot_grads(self, slot_idx, grads):
        import jax.numpy as jnp

        g = jnp.asarray(grads).reshape(len(slot_idx), -1)
        self._gacc = self._gacc.at[jnp.asarray(slot_idx)].add(g)

    def end_pass(self, assign=False):
        """Sync the pass back to the host PS and clear the cache.

        assign=False: ONE merged gradient push (downpour per-pass step —
        the PS applies its optimizer to the summed grad).
        assign=True: write the VALUES back (ps_gpu_wrapper EndPass when
        the device optimizer updated the cached rows per step; the PS
        becomes a value store for the pass)."""
        if self._keys is None:
            return
        if assign:
            vals = np.asarray(self._rows)[:self._n_real]
            self.client.assign(self.table_id, self._keys, vals)
            self.pushes += 1
        else:
            g = np.asarray(self._gacc)[:self._n_real]
            nz = np.any(g != 0, axis=1)
            if nz.any():
                self.client.push(self.table_id, self._keys[nz], g[nz],
                                 lr=self.lr)
                self.pushes += 1
        self._keys = None
        self._slot_of = {}
        self._rows = self._gacc = None


class HeterCache:
    """Capacity-bounded device embedding cache shared by concurrent
    workers.

    Reference: paddle/fluid/framework/fleet/heter_ps/heter_comm.h (the
    per-device cache heter_comm pulls into and merges grads through) +
    ps_gpu_wrapper.cc. Three properties the pass-scoped DevicePassCache
    lacks, per VERDICT r4 #4:

    * eviction — at most `capacity` rows live on device (one fixed
      [capacity, dim] slab, so the jitted lookups keep a static shape);
      victims are chosen LRU or LFU and their unsynced gradients are
      written back before the slot is reused.
    * batched fault aggregation — a worker that misses becomes the fault
      LEADER, waits `fault_window_s` for concurrently-missing workers to
      register their ids, then issues ONE bulk pull for the union
      (heter_comm's merged pull); followers block until their rows are
      installed.
    * write-back coalescing — evicted dirty rows buffer host-side and
      push in ONE rpc per `flush_rows` batch (plus a final flush()), not
      one push per eviction.

    Stats (hits / misses / fault_pulls / writeback_pushes) expose the
    cache behavior for tests and observability.
    """

    def __init__(self, client, table_id: int, dim: int, capacity: int,
                 lr: float = -1.0, policy: str = "lru",
                 flush_rows: int = 256, fault_window_s: float = 0.002):
        if policy not in ("lru", "lfu"):
            raise ValueError(f"policy must be 'lru' or 'lfu', got {policy!r}")
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.lr = float(lr)
        self.policy = policy
        self.flush_rows = int(flush_rows)
        self.fault_window_s = float(fault_window_s)

        import jax.numpy as jnp

        self._rows = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._gacc = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._keys = np.full(self.capacity, -1, np.int64)  # slot -> key
        self._slot_of: dict = {}                           # key -> slot
        self._free = list(range(self.capacity - 1, -1, -1))
        self._stamp = np.zeros(self.capacity, np.int64)    # lru tick / lfu count
        self._dirty = np.zeros(self.capacity, bool)
        self._tick = 0

        self._lock = threading.RLock()          # metadata + device slab
        self._cv = threading.Condition(self._lock)
        self._fault_pending: set = set()
        self._fault_leader = False
        self._wb_keys: list = []                # coalesced write-back buffer
        self._wb_grads: list = []

        self.hits = 0
        self.misses = 0
        self.fault_pulls = 0      # host-PS pull rpcs
        self.writeback_pushes = 0  # host-PS push rpcs
        self.evictions = 0
        # bound children (metrics bind() idiom): one attr-add per lookup
        self._m_hits = _m_cache_hits.labels(table=str(self.table_id))
        self._m_misses = _m_cache_misses.labels(table=str(self.table_id))

    # -- internals (call with self._lock held) ------------------------------
    def _touch(self, slots):
        if self.policy == "lru":
            self._tick += 1
            self._stamp[slots] = self._tick
        else:
            np.add.at(self._stamp, slots, 1)

    def _evict_batch(self, k: int) -> list:
        """Reclaim the k coldest slots at once, buffering their unsynced
        grads for the coalesced write-back (the RPC itself happens outside
        the lock via _take_writeback, so hit-path lookups never stall on
        the network). Batched on purpose: the dirty-grad device->host pull
        is ONE gather for all victims, not one sync per evicted row — the
        per-row sync made eviction-heavy (skewed, capacity-bound) passes
        eviction-dominated (ISSUE 20 bench)."""
        live = np.flatnonzero(self._keys >= 0)
        order = np.argsort(self._stamp[live], kind="stable")[:int(k)]
        victims = live[order]
        dirty = victims[self._dirty[victims]]
        if dirty.size:
            import jax.numpy as jnp

            gacc_host = np.asarray(jnp.take(       # one bucketed device pull
                self._gacc, jnp.asarray(_pow2_pad(dirty, 0)),
                axis=0))[:dirty.size]
            for s, row in zip(dirty.tolist(), gacc_host):
                self._wb_keys.append(int(self._keys[s]))
                self._wb_grads.append(row)
            self._dirty[dirty] = False
        for s in victims.tolist():
            del self._slot_of[int(self._keys[s])]
        self._keys[victims] = -1
        self._stamp[victims] = 0
        self.evictions += int(victims.size)
        return victims.tolist()

    def _evict_one(self) -> int:
        return self._evict_batch(1)[0]

    def _take_writeback(self, force=False):
        """(lock held) Swap out the coalesce buffer when it is due; the
        caller pushes the returned payload AFTER releasing the lock."""
        if not self._wb_keys or (
                not force and len(self._wb_keys) < self.flush_rows):
            return None
        payload = (np.asarray(self._wb_keys, np.uint64),
                   np.stack(self._wb_grads))
        self._wb_keys, self._wb_grads = [], []
        return payload

    def _push_payload(self, payload):
        """(lock NOT held) One batched push rpc for a write-back payload."""
        if payload is None:
            return
        self.client.push(self.table_id, payload[0], payload[1], lr=self.lr)
        with self._lock:
            self.writeback_pushes += 1

    def _install(self, keys: np.ndarray, rows: np.ndarray):
        import jax.numpy as jnp

        fresh, seen = [], set()
        for k in keys.tolist():
            k = int(k)
            if k not in self._slot_of and k not in seen:
                seen.add(k)
                fresh.append(k)  # else another fault round installed it
        need = len(fresh) - len(self._free)
        reclaimed = self._evict_batch(need) if need > 0 else []
        slots = []
        for k in fresh:
            s = self._free.pop() if self._free else reclaimed.pop()
            self._slot_of[k] = s
            self._keys[s] = k
            # stamp NOW: a slot left at stamp 0 would be the next argmin,
            # letting a later round evict this install prematurely (all of
            # THIS round's victims were chosen before any install)
            self._touch(np.asarray([s]))
            slots.append((s, k))
        if slots:
            idx = np.asarray([s for s, _ in slots], np.int32)
            order = {int(k): i for i, k in enumerate(keys.tolist())}
            src = np.asarray([rows[order[k]] for _, k in slots], np.float32)
            # bucketed scatter: pad indices OOB (dropped) so install size
            # doesn't mint a new compiled program per distinct miss count
            pad_idx = jnp.asarray(_pow2_pad(idx, self.capacity))
            pad_src = np.zeros((pad_idx.shape[0], src.shape[1]), np.float32)
            pad_src[:len(idx)] = src
            self._rows = self._rows.at[pad_idx].set(jnp.asarray(pad_src),
                                                    mode="drop")
            self._gacc = self._gacc.at[pad_idx].set(0.0, mode="drop")

    # -- fault path ----------------------------------------------------------
    def _fault(self, missing):
        """Batched fault: register ids, elect a leader, ONE pull for the
        union of every concurrently-faulting worker's misses."""
        with self._cv:
            self._fault_pending.update(int(m) for m in missing)
            while True:
                if all(int(m) in self._slot_of for m in missing):
                    return  # someone else's round covered us
                if not self._fault_leader:
                    self._fault_leader = True
                    break
                self._cv.wait(timeout=5.0)
        try:
            if self.fault_window_s > 0:
                time.sleep(self.fault_window_s)  # let peers join the batch
            with self._cv:
                own = sorted({int(m) for m in missing}
                             - set(self._slot_of))
                others = sorted(k for k in self._fault_pending
                                if k not in self._slot_of
                                and k not in set(own))
                # the batch must fit the slab: the leader's OWN ids come
                # first (a single caller never exceeds capacity — lookup
                # guards that), then as many peers' as fit; the remainder
                # stays pending for the next leader round, so an
                # over-capacity UNION degrades to sequential service
                # instead of failing or thrashing
                batch_list = (own + others)[:self.capacity]
                self._fault_pending.difference_update(batch_list)
                batch = np.asarray(sorted(batch_list), np.uint64)
            payload = None
            if batch.size:
                rows = np.asarray(self.client.pull(self.table_id, batch),
                                  np.float32)
                with self._cv:
                    self.fault_pulls += 1
                    self._install(batch, rows)
                    payload = self._take_writeback()
            self._push_payload(payload)  # outside the lock
        finally:
            with self._cv:
                self._fault_leader = False
                self._cv.notify_all()

    # -- public API ----------------------------------------------------------
    def lookup(self, ids):
        """[*ids.shape, dim] device gather; faults (batched) on misses."""
        import jax.numpy as jnp

        flat = np.asarray(ids, np.int64).reshape(-1)
        uniq = len(set(flat.tolist()))
        if uniq > self.capacity:
            raise ValueError(
                f"one lookup touches {uniq} unique ids but capacity is "
                f"{self.capacity}; they cannot be device-resident at once")
        counted = False
        for _attempt in range(64):
            with self._lock:
                missing = [k for k in flat.tolist()
                           if k not in self._slot_of]
                if not counted:
                    # count each id once, against its FIRST outcome —
                    # re-checks after a fault are not new hits
                    counted = True
                    self.misses += len(missing)
                    self.hits += len(flat) - len(missing)
                    if missing:
                        self._m_misses.inc(len(missing))
                    if len(flat) > len(missing):
                        self._m_hits.inc(len(flat) - len(missing))
                if not missing:
                    slots = np.asarray(
                        [self._slot_of[k] for k in flat.tolist()], np.int32)
                    self._touch(np.unique(slots))
                    rows = self._rows  # immutable snapshot
                    break
            self._fault(missing)
        else:
            raise RuntimeError(
                "lookup could not stabilize its working set after 64 "
                "fault rounds — concurrent workers keep evicting each "
                "other's rows; raise capacity")
        out = jnp.take(rows, jnp.asarray(slots), axis=0)
        return out.reshape(tuple(np.shape(ids)) + (self.dim,))

    def push_grads(self, ids, grads):
        """Scatter-add grads for cached rows (device accumulate; the host
        PS sees them at eviction or flush — write-back semantics)."""
        import jax.numpy as jnp

        flat = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(len(flat), -1)
        with self._lock:
            in_cache = np.asarray([k in self._slot_of for k in flat.tolist()])
            if not in_cache.all():
                # a concurrent worker's fault may have evicted a row
                # between our forward and backward — its grad goes to the
                # coalesce buffer instead of crashing the step (the PS
                # merge at push time is identical either way)
                for k, row in zip(flat[~in_cache].tolist(),
                                  g[~in_cache]):
                    self._wb_keys.append(int(k))
                    self._wb_grads.append(row)
            if in_cache.any():
                slots = np.asarray(
                    [self._slot_of[int(k)] for k in flat[in_cache]],
                    np.int32)
                # bucketed scatter-add (pad rows add at an OOB index →
                # dropped): stable shapes across varying batch overlap
                pad_idx = jnp.asarray(_pow2_pad(slots, self.capacity))
                pad_g = np.zeros((pad_idx.shape[0], g.shape[1]), np.float32)
                pad_g[:len(slots)] = g[in_cache]
                self._gacc = self._gacc.at[pad_idx].add(jnp.asarray(pad_g),
                                                        mode="drop")
                self._dirty[np.unique(slots)] = True
            payload = self._take_writeback()
        self._push_payload(payload)

    def flush(self):
        """Write back every dirty row + the coalesced eviction buffer
        (end-of-pass / checkpoint boundary). The rpc runs outside the
        lock."""
        with self._lock:
            dirty = np.flatnonzero(self._dirty & (self._keys >= 0))
            if dirty.size:
                import jax.numpy as jnp

                self._wb_keys.extend(int(k) for k in self._keys[dirty])
                gacc_host = np.asarray(jnp.take(
                    self._gacc, jnp.asarray(_pow2_pad(dirty, 0)),
                    axis=0))[:dirty.size]
                self._wb_grads.extend(gacc_host)
                self._gacc = self._gacc.at[
                    jnp.asarray(_pow2_pad(dirty, self.capacity))].set(
                        0.0, mode="drop")
                self._dirty[dirty] = False
            payload = self._take_writeback(force=True)
        self._push_payload(payload)

    @property
    def live_rows(self):
        with self._lock:
            return len(self._slot_of)

    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

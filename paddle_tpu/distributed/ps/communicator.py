"""Trainer-side PS communicators: async send-queue and geo delta-sync.

Reference: paddle/fluid/distributed/ps/service/communicator/communicator.h —
AsyncCommunicator (:402): per-table send queues, a background thread merging
`max_merge_var_num` pending gradients before each RPC; GeoCommunicator
(:566): trainers train on local replicas and exchange parameter DELTAS every
k steps (geo-SGD).

TPU framing: the dense model lives on-chip inside the jitted step; only the
host-side sparse-table traffic flows through these objects, so the merge
thread hides PS RPC latency behind device compute.
"""
from __future__ import annotations

import queue as queue_mod
import threading
from typing import Dict, Optional

import numpy as np

__all__ = ["Communicator", "AsyncCommunicator", "GeoCommunicator",
           "merge_sparse"]


def _record_rpc(op, table_id, keys, grads=None):
    """FLAGS_enable_rpc_profiler: the reference's per-RPC profiler spans,
    reinterpreted as structured EventLog records on the PS push/pull path
    (+ a counter either way)."""
    from ...observability import get_event_log, rpc_profiler_enabled
    from ...observability.metrics import get_registry

    get_registry().counter("ps_rpcs_total", help="PS push/pull RPCs issued",
                           labels=("op",)).labels(op=op).inc()
    if rpc_profiler_enabled():
        get_event_log().debug(
            "ps_rpc", op=op, table_id=int(table_id), n_keys=int(keys.size),
            bytes=int(grads.nbytes) if grads is not None else None)


def merge_sparse(keys: np.ndarray, grads: np.ndarray):
    """MergeAdd on the host: sum gradient rows of duplicate keys. Public
    seam — the sharded pipeline client merges before quantizing so
    duplicate-id grads SUM (never last-write-win) regardless of backend."""
    uniq, inv = np.unique(keys, return_inverse=True)
    out = np.zeros((uniq.size, grads.shape[1]), grads.dtype)
    np.add.at(out, inv, grads)
    return uniq, out


_merge_sparse = merge_sparse  # back-compat internal name


class Communicator:
    """Synchronous base: push goes straight to the client (the reference's
    SyncCommunicator role). Also the factory the fleet runtime uses."""

    def __init__(self, client, mode: str = "sync", **configs):
        self.client = client
        self.mode = mode
        self.running = False

    @staticmethod
    def create(client, strategy=None):
        """Pick the mode from a DistributedStrategy (the_one_ps.py logic):
        a_sync=False → sync; a_sync=True → async; a_sync_configs.k_steps>0
        → geo."""
        if strategy is None or not getattr(strategy, "a_sync", False):
            return Communicator(client)
        k = int(getattr(strategy, "a_sync_configs", {}).get("k_steps", 0))
        if k > 0:
            return GeoCommunicator(client, k_steps=k)
        cfg = getattr(strategy, "a_sync_configs", {}) or {}
        from ...framework.flags import flag

        return AsyncCommunicator(
            client,
            max_merge_var_num=int(cfg.get(
                "max_merge_var_num",
                flag("FLAGS_communicator_max_merge_var_num", 20))),
            send_wait_times=float(cfg.get(
                "send_wait_times",
                flag("FLAGS_communicator_send_wait_times", 0.005))),
        )

    def start(self):
        self.running = True

    def stop(self):
        self.running = False

    def is_running(self):
        return self.running

    def push_sparse(self, table_id, keys, grads, lr=-1.0):
        keys, grads = _merge_sparse(np.asarray(keys, np.uint64).reshape(-1),
                                    np.asarray(grads, np.float32))
        _record_rpc("push_sparse", table_id, keys, grads)
        self.client.push(table_id, keys, grads, lr=lr)

    def pull_sparse(self, table_id, keys):
        _record_rpc("pull_sparse", table_id, np.asarray(keys))
        return self.client.pull(table_id, keys)

    def flush(self):
        pass


class AsyncCommunicator(Communicator):
    """communicator.h:402 — trainer enqueues; a daemon merges up to
    `max_merge_var_num` pending pushes per table, then RPCs once."""

    def __init__(self, client, max_merge_var_num=20, send_wait_times=0.005,
                 send_queue_size=None, **configs):
        super().__init__(client, mode="async")
        from ...framework.flags import flag

        self.max_merge = int(max_merge_var_num)
        self.wait = float(send_wait_times)
        # bounded send queue (communicator.h send_queue_size): a stalled PS
        # back-pressures the trainer instead of buffering without limit
        qsize = int(send_queue_size if send_queue_size is not None
                    else flag("FLAGS_communicator_send_queue_size", 20))
        self._q: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=max(qsize, 1) * self.max_merge)
        self._thread: Optional[threading.Thread] = None
        self._err = []
        self._drained = threading.Event()
        self._drained.set()

    def start(self):
        if self.running:
            return
        self.running = True
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    def stop(self):
        if not self.running:
            return
        self.flush()
        self.running = False
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._err:
            raise self._err[0]

    def push_sparse(self, table_id, keys, grads, lr=-1.0):
        if not self.running:
            return Communicator.push_sparse(self, table_id, keys, grads, lr)
        self._drained.clear()
        self._q.put((int(table_id),
                     np.asarray(keys, np.uint64).reshape(-1),
                     np.asarray(grads, np.float32), float(lr)))

    def flush(self):
        """Block until every queued push has been sent (barrier before
        save/eval, the reference's BarrierWithTable)."""
        self._drained.wait(timeout=60)
        if self._err:
            raise self._err[0]

    def _send_loop(self):
        while True:
            try:
                item = self._q.get(timeout=self.wait)
            except queue_mod.Empty:
                if self._q.empty():
                    self._drained.set()
                continue
            if item is None:
                self._drained.set()
                return
            # merge a window of pushes for the same table
            batch = [item]
            while len(batch) < self.max_merge:
                try:
                    nxt = self._q.get_nowait()
                except queue_mod.Empty:
                    break
                if nxt is None:
                    self._q.put(None)
                    break
                if nxt[0] != item[0] or nxt[3] != item[3]:
                    self._q.put(nxt)  # different table/lr: next window
                    break
                batch.append(nxt)
            try:
                keys = np.concatenate([b[1] for b in batch])
                grads = np.concatenate([b[2] for b in batch])
                keys, grads = _merge_sparse(keys, grads)
                _record_rpc("push_sparse_merged", item[0], keys, grads)
                self.client.push(item[0], keys, grads, lr=item[3])
            except Exception as e:  # surface on flush/stop
                self._err.append(e)
                self._drained.set()
                return
            if self._q.empty():
                self._drained.set()


class GeoCommunicator(Communicator):
    """communicator.h:566 — local training, delta exchange every k steps.

    Sparse tables: the trainer keeps a local row cache; every k_steps the
    accumulated (new - synced) row deltas push to the PS and fresh rows pull
    back, so trainers converge geographically ("geo-SGD")."""

    def __init__(self, client, k_steps=100, **configs):
        super().__init__(client, mode="geo")
        self.k_steps = int(k_steps)
        self._local: Dict[int, Dict[int, np.ndarray]] = {}   # table → row → val
        self._synced: Dict[int, Dict[int, np.ndarray]] = {}
        self._step = 0

    def pull_sparse(self, table_id, keys):
        """Serve from the local replica; fault in missing rows from the PS."""
        t = int(table_id)
        local = self._local.setdefault(t, {})
        synced = self._synced.setdefault(t, {})
        keys = np.asarray(keys, np.uint64).reshape(-1)
        missing = [k for k in keys.tolist() if k not in local]
        if missing:
            rows = self.client.pull(t, np.asarray(missing, np.uint64))
            for k, r in zip(missing, rows):
                local[k] = r.astype(np.float32).copy()
                synced[k] = r.astype(np.float32).copy()
        return np.stack([local[k] for k in keys.tolist()])

    def push_sparse(self, table_id, keys, grads, lr=-1.0):
        """Apply the gradient LOCALLY; sync deltas every k steps."""
        t = int(table_id)
        local = self._local.setdefault(t, {})
        keys = np.asarray(keys, np.uint64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        eta = lr if lr > 0 else 0.05
        mk, mg = _merge_sparse(keys, grads)
        for k, g in zip(mk.tolist(), mg):
            if k not in local:
                self.pull_sparse(t, np.asarray([k], np.uint64))
            local[k] = local[k] - eta * g
        self._step += 1
        if self._step % self.k_steps == 0:
            self.flush()

    def flush(self):
        """Push deltas, pull fresh values (the geo sync round)."""
        for t, local in self._local.items():
            synced = self._synced[t]
            rows, deltas = [], []
            for k, v in local.items():
                d = v - synced[k]
                if np.any(d):
                    rows.append(k)
                    deltas.append(d)
            if rows:
                # server-side atomic += : a client-side pull+assign would
                # lose concurrent workers' deltas (read-modify-write race)
                self.client.add(t, np.asarray(rows, np.uint64),
                                np.stack(deltas))
            if not local:
                continue
            # recv side: refresh EVERY cached row, dirty or not — other
            # trainers' deltas must reach this replica even in rounds where
            # it pushed nothing (communicator.h RecvByCommunicator)
            all_keys = np.asarray(list(local.keys()), np.uint64)
            fresh = self.client.pull(t, all_keys)
            for k, r in zip(all_keys.tolist(), fresh):
                local[k] = r.astype(np.float32).copy()
                synced[k] = r.astype(np.float32).copy()

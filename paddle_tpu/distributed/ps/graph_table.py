"""Graph table — graph storage + neighbor sampling for GNN training.

Reference: paddle/fluid/distributed/ps/table/common_graph_table.cc (~4k LoC):
edge/node storage sharded by id, uniform and weighted neighbor sampling,
node-feature serving — the backend of paddle.distributed.graph ops
(graph_sample_neighbors etc.).

TPU-native split: sampling is host work (pointer chasing — the TPU would
hate it); results arrive as padded [n, size] id arrays + counts so the
downstream gather/aggregate runs as dense XLA ops. Storage is CSR-style
numpy (vectorized sampling), sharded by splitmix64 like the sparse table.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["GraphTable"]


class GraphTable:
    def __init__(self, feature_dim: int = 0, seed: int = 0):
        self._adj: Dict[int, np.ndarray] = {}      # node → neighbor ids
        self._w: Dict[int, np.ndarray] = {}        # node → edge weights
        self._feat: Dict[int, np.ndarray] = {}     # node → feature vec
        self.feature_dim = int(feature_dim)
        self._rs = np.random.RandomState(seed)

    # -- construction --------------------------------------------------------
    def add_edges(self, src, dst, weights=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = (np.asarray(weights, np.float32).reshape(-1)
             if weights is not None else np.ones(src.size, np.float32))
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        uniq, starts = np.unique(src, return_index=True)
        ends = np.append(starts[1:], src.size)
        for u, a, b in zip(uniq.tolist(), starts, ends):
            if u in self._adj:
                self._adj[u] = np.concatenate([self._adj[u], dst[a:b]])
                self._w[u] = np.concatenate([self._w[u], w[a:b]])
            else:
                self._adj[u] = dst[a:b].copy()
                self._w[u] = w[a:b].copy()

    def set_node_features(self, ids, features):
        ids = np.asarray(ids, np.int64).reshape(-1)
        features = np.asarray(features, np.float32).reshape(ids.size, -1)
        if self.feature_dim == 0:
            self.feature_dim = features.shape[1]
        for i, f in zip(ids.tolist(), features):
            self._feat[i] = f.copy()

    # -- queries --------------------------------------------------------------
    def degree(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        return np.asarray([self._adj.get(i, np.empty(0)).size
                           for i in ids.tolist()], np.int64)

    def sample_neighbors(self, ids, sample_size: int, weighted=False,
                         replace=False):
        """Padded [n, sample_size] neighbor ids (-1 pad) + counts [n]
        (common_graph_table.cc random_sample_neighbors)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((ids.size, sample_size), -1, np.int64)
        cnt = np.zeros(ids.size, np.int64)
        for r, node in enumerate(ids.tolist()):
            nbrs = self._adj.get(node)
            if nbrs is None or nbrs.size == 0:
                continue
            k = sample_size if replace else min(sample_size, nbrs.size)
            if weighted:
                p = self._w[node] / self._w[node].sum()
                pick = self._rs.choice(nbrs.size, size=k, replace=replace,
                                       p=p)
            elif nbrs.size <= k and not replace:
                pick = np.arange(nbrs.size)
            else:
                pick = self._rs.choice(nbrs.size, size=k, replace=replace)
            out[r, :k] = nbrs[pick]
            cnt[r] = k
        return out, cnt

    def get_node_features(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros((ids.size, self.feature_dim), np.float32)
        for r, i in enumerate(ids.tolist()):
            f = self._feat.get(i)
            if f is not None:
                out[r] = f
        return out

    def random_sample_nodes(self, n: int):
        keys = np.fromiter(self._adj.keys(), np.int64)
        if keys.size == 0:
            return np.empty(0, np.int64)
        return keys[self._rs.choice(keys.size, size=min(n, keys.size),
                                    replace=False)]

    def __len__(self):
        return len(self._adj)

"""Graph table — graph storage + neighbor sampling for GNN training.

Reference: paddle/fluid/distributed/ps/table/common_graph_table.cc (~4k LoC):
edge/node storage sharded by id, uniform and weighted neighbor sampling,
node-feature serving, paginated node listing (pull_graph_list), a neighbor-
sample cache (make_neighbor_sample_cache), and the random-walk surface the
GNN stack builds on (deepwalk/metapath walks in the fleet graph engine,
paddle/fluid/framework/fleet/heter_ps/graph_gpu_wrapper.h).

TPU-native split: sampling/walks are host work (pointer chasing — the TPU
would hate it); results arrive as padded [n, size] id arrays + counts so the
downstream gather/aggregate runs as dense XLA ops. Storage is per-node numpy
adjacency keyed by edge type, sharded across PS servers by splitmix64 like
the sparse table (PsClient routes by node id).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["GraphTable"]

_DEFAULT = ""  # the untyped edge set


class GraphTable:
    def __init__(self, feature_dim: int = 0, seed: int = 0):
        # etype -> node -> neighbor ids / edge weights
        self._adj: Dict[str, Dict[int, np.ndarray]] = {}
        self._w: Dict[str, Dict[int, np.ndarray]] = {}
        self._feat: Dict[int, np.ndarray] = {}     # node → feature vec
        self.feature_dim = int(feature_dim)
        self._rs = np.random.RandomState(seed)
        # neighbor-sample cache (make_neighbor_sample_cache): per (node,
        # size, flavor) rows with a query-count TTL
        self._cache: Optional[OrderedDict] = None
        self._cache_limit = 0
        self._cache_ttl = 0
        self._cache_clock = 0

    def _layer(self, etype: str):
        a = self._adj.setdefault(etype, {})
        w = self._w.setdefault(etype, {})
        return a, w

    # -- construction --------------------------------------------------------
    def add_edges(self, src, dst, weights=None, etype: str = _DEFAULT):
        if self._cache is not None:  # cached rows predate the new edges
            self._cache.clear()
        adj, wmap = self._layer(etype)
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = (np.asarray(weights, np.float32).reshape(-1)
             if weights is not None else np.ones(src.size, np.float32))
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        uniq, starts = np.unique(src, return_index=True)
        ends = np.append(starts[1:], src.size)
        for u, a, b in zip(uniq.tolist(), starts, ends):
            if u in adj:
                adj[u] = np.concatenate([adj[u], dst[a:b]])
                wmap[u] = np.concatenate([wmap[u], w[a:b]])
            else:
                adj[u] = dst[a:b].copy()
                wmap[u] = w[a:b].copy()

    def set_node_features(self, ids, features):
        ids = np.asarray(ids, np.int64).reshape(-1)
        features = np.asarray(features, np.float32).reshape(ids.size, -1)
        if self.feature_dim == 0:
            self.feature_dim = features.shape[1]
        for i, f in zip(ids.tolist(), features):
            self._feat[i] = f.copy()

    def clear_nodes(self, etype: Optional[str] = None):
        """common_graph_table.cc clear_nodes."""
        if etype is None:
            self._adj.clear()
            self._w.clear()
            self._feat.clear()
        else:
            self._adj.pop(etype, None)
            self._w.pop(etype, None)
        if self._cache is not None:
            self._cache.clear()

    # -- queries --------------------------------------------------------------
    def degree(self, ids, etype: str = _DEFAULT):
        adj = self._adj.get(etype, {})
        ids = np.asarray(ids, np.int64).reshape(-1)
        return np.asarray([adj.get(i, np.empty(0)).size
                           for i in ids.tolist()], np.int64)

    def make_neighbor_sample_cache(self, size_limit: int, ttl: int):
        """Cache sample rows per (node, size, flavor) for `ttl` cache
        queries (common_graph_table.h make_neighbor_sample_cache — trades
        sample freshness for pointer-chasing cost on hot nodes)."""
        self._cache = OrderedDict()
        self._cache_limit = max(1, int(size_limit))
        self._cache_ttl = int(ttl)
        self._cache_clock = 0

    def _cached_row(self, key):
        if self._cache is None:
            return None
        hit = self._cache.get(key)
        if hit is None:
            return None
        row, cnt, stamp = hit
        if self._cache_clock - stamp >= self._cache_ttl:
            del self._cache[key]
            return None
        return row, cnt

    def _cache_put(self, key, row, cnt):
        if self._cache is None:
            return
        while len(self._cache) >= self._cache_limit:
            self._cache.popitem(last=False)
        self._cache[key] = (row, cnt, self._cache_clock)

    def sample_neighbors(self, ids, sample_size: int, weighted=False,
                         replace=False, etype: str = _DEFAULT):
        """Padded [n, sample_size] neighbor ids (-1 pad) + counts [n]
        (common_graph_table.cc random_sample_neighbors)."""
        adj = self._adj.get(etype, {})   # read path: never create layers
        wmap = self._w.get(etype, {})
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((ids.size, sample_size), -1, np.int64)
        cnt = np.zeros(ids.size, np.int64)
        if self._cache is not None:
            self._cache_clock += 1
        for r, node in enumerate(ids.tolist()):
            ckey = (etype, node, sample_size, weighted, replace)
            hit = self._cached_row(ckey)
            if hit is not None:
                out[r], cnt[r] = hit
                continue
            nbrs = adj.get(node)
            if nbrs is None or nbrs.size == 0:
                continue
            k = sample_size if replace else min(sample_size, nbrs.size)
            if weighted:
                p = wmap[node] / wmap[node].sum()
                pick = self._rs.choice(nbrs.size, size=k, replace=replace,
                                       p=p)
            elif nbrs.size <= k and not replace:
                pick = np.arange(nbrs.size)
            else:
                pick = self._rs.choice(nbrs.size, size=k, replace=replace)
            out[r, :k] = nbrs[pick]
            cnt[r] = k
            self._cache_put(ckey, out[r].copy(), k)
        return out, cnt

    def get_node_features(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros((ids.size, self.feature_dim), np.float32)
        for r, i in enumerate(ids.tolist()):
            f = self._feat.get(i)
            if f is not None:
                out[r] = f
        return out

    def random_sample_nodes(self, n: int, etype: str = _DEFAULT):
        adj = self._adj.get(etype, {})
        keys = np.fromiter(adj.keys(), np.int64, count=len(adj))
        if keys.size == 0:
            return np.empty(0, np.int64)
        return keys[self._rs.choice(keys.size, size=min(n, keys.size),
                                    replace=False)]

    def pull_graph_list(self, start: int, size: int, etype: str = _DEFAULT):
        """Paginated, sorted node listing (common_graph_table.cc
        pull_graph_list) — the full-graph scan GNN epoch loops use."""
        adj = self._adj.get(etype, {})
        keys = np.sort(np.fromiter(adj.keys(), np.int64, count=len(adj)))
        return keys[int(start):int(start) + int(size)]

    # -- random walks ---------------------------------------------------------
    def random_walk(self, start_ids, walk_len: int, etype: str = _DEFAULT,
                    weighted=False):
        """Uniform (or edge-weighted) walks: [n, walk_len+1] int64, -1
        padded once a walk hits a node with no out-edges (deepwalk walks,
        graph_gpu_wrapper.h graph_walk path)."""
        adj = self._adj.get(etype, {})
        wmap = self._w.get(etype, {})
        start = np.asarray(start_ids, np.int64).reshape(-1)
        walks = np.full((start.size, walk_len + 1), -1, np.int64)
        walks[:, 0] = start
        for r, node in enumerate(start.tolist()):
            cur = node
            for step in range(1, walk_len + 1):
                nbrs = adj.get(cur)
                if nbrs is None or nbrs.size == 0:
                    break
                if weighted:
                    p = wmap[cur] / wmap[cur].sum()
                    cur = int(nbrs[self._rs.choice(nbrs.size, p=p)])
                else:
                    cur = int(nbrs[self._rs.randint(nbrs.size)])
                walks[r, step] = cur
        return walks

    def node2vec_walk(self, start_ids, walk_len: int, p: float = 1.0,
                      q: float = 1.0, etype: str = _DEFAULT):
        """Second-order node2vec walks: the unnormalized transition weight
        to x from cur (having arrived from prev) is 1/p if x == prev, 1 if
        x is a neighbor of prev, else 1/q."""
        adj = self._adj.get(etype, {})
        nbr_sets: Dict[int, set] = {}

        def nset(u):
            s = nbr_sets.get(u)
            if s is None:
                s = set(adj.get(u, np.empty(0, np.int64)).tolist())
                nbr_sets[u] = s
            return s

        start = np.asarray(start_ids, np.int64).reshape(-1)
        walks = np.full((start.size, walk_len + 1), -1, np.int64)
        walks[:, 0] = start
        for r, node in enumerate(start.tolist()):
            prev, cur = None, node
            for step in range(1, walk_len + 1):
                nbrs = adj.get(cur)
                if nbrs is None or nbrs.size == 0:
                    break
                if prev is None:
                    nxt = int(nbrs[self._rs.randint(nbrs.size)])
                else:
                    pset = nset(prev)
                    w = np.empty(nbrs.size, np.float64)
                    for i, x in enumerate(nbrs.tolist()):
                        if x == prev:
                            w[i] = 1.0 / p
                        elif x in pset:
                            w[i] = 1.0
                        else:
                            w[i] = 1.0 / q
                    w /= w.sum()
                    nxt = int(nbrs[self._rs.choice(nbrs.size, p=w)])
                walks[r, step] = nxt
                prev, cur = cur, nxt
        return walks

    def meta_path_walk(self, start_ids, meta_path: Sequence[str]):
        """Heterogeneous walks following edge types in order ("u2i","i2u",
        ...): [n, len(meta_path)+1] (the metapath sampling the reference's
        graph engine feeds walk-based recommenders)."""
        start = np.asarray(start_ids, np.int64).reshape(-1)
        walks = np.full((start.size, len(meta_path) + 1), -1, np.int64)
        walks[:, 0] = start
        for r, node in enumerate(start.tolist()):
            cur = node
            for step, et in enumerate(meta_path, start=1):
                nbrs = self._adj.get(et, {}).get(cur)
                if nbrs is None or nbrs.size == 0:
                    break
                cur = int(nbrs[self._rs.randint(nbrs.size)])
                walks[r, step] = cur
        return walks

    # -- lifecycle ------------------------------------------------------------
    def save(self, path: str):
        """npz snapshot: per-etype CSR arrays + node features."""
        payload = {}
        etypes = list(self._adj.keys())
        payload["etypes"] = np.array(etypes, dtype="U64")
        for idx, et in enumerate(etypes):
            adj = self._adj[et]
            nodes = np.fromiter(adj.keys(), np.int64, count=len(adj))
            nodes.sort()
            counts = np.asarray([adj[n].size for n in nodes.tolist()],
                                np.int64)
            payload[f"nodes_{idx}"] = nodes
            payload[f"counts_{idx}"] = counts
            if nodes.size:
                payload[f"dst_{idx}"] = np.concatenate(
                    [adj[n] for n in nodes.tolist()])
                payload[f"w_{idx}"] = np.concatenate(
                    [self._w[et][n] for n in nodes.tolist()])
            else:
                payload[f"dst_{idx}"] = np.empty(0, np.int64)
                payload[f"w_{idx}"] = np.empty(0, np.float32)
        fids = np.fromiter(self._feat.keys(), np.int64,
                           count=len(self._feat))
        payload["feat_ids"] = fids
        payload["feat_vals"] = (np.stack([self._feat[i] for i in
                                          fids.tolist()])
                                if fids.size else
                                np.empty((0, self.feature_dim), np.float32))
        np.savez(path, **payload)

    def load(self, path: str):
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        data = np.load(path)
        self._adj.clear()
        self._w.clear()
        self._feat.clear()
        if self._cache is not None:  # stale samples must not outlive the
            self._cache.clear()      # graph they were drawn from
        for idx, et in enumerate(data["etypes"].tolist()):
            nodes = data[f"nodes_{idx}"]
            counts = data[f"counts_{idx}"]
            dst = data[f"dst_{idx}"]
            w = data[f"w_{idx}"]
            adj, wmap = self._layer(str(et))
            off = 0
            for n, c in zip(nodes.tolist(), counts.tolist()):
                adj[n] = dst[off:off + c].copy()
                wmap[n] = w[off:off + c].copy()
                off += c
        fids = data["feat_ids"]
        fvals = data["feat_vals"]
        if fvals.size:
            self.feature_dim = fvals.shape[1]
        for i, f in zip(fids.tolist(), fvals):
            self._feat[i] = np.asarray(f, np.float32)

    def __len__(self):
        nodes = set()
        for adj in self._adj.values():
            nodes.update(adj.keys())
        return len(nodes)

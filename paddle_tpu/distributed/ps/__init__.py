"""Parameter-server subsystem.

Reference: paddle/fluid/distributed/ps/ — BrpcPsServer/BrpcPsClient push/pull
RPC (brpc_ps_server.cc), MemorySparseTable (table/memory_sparse_table.cc),
TheOnePSRuntime (distributed/ps/the_one_ps.py).

TPU-native split: the data-plane hot path (hashing, row init, sparse
optimizer updates) is native C++ (paddle_tpu/core/csrc/sparse_table.cc); the
transport is a length-prefixed binary protocol over TCP sockets (the brpc
role); dense training stays on the TPU via XLA — only the CTR-scale sparse
embeddings live host-side.
"""
from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional

import numpy as np

from ...core.table import SparseTable
from .graph_table import GraphTable
from .heter_trainer import HeterPassTrainer, heter_embedding

__all__ = ["PsServer", "PsClient", "TheOnePSRuntime", "LocalPs",
           "GraphTable", "HeterPassTrainer", "heter_embedding",
           "distributed_lookup_table", "distributed_push_sparse"]


# --------------------------------------------------------------------------
# wire protocol: [8-byte length][framed message] — the sendrecv.proto analog.
# A restricted tag-length-value codec (NOT pickle): only scalars, strings,
# lists/dicts and numeric numpy arrays can cross the wire, so a crafted frame
# cannot execute code on the server. Mirrors the reference's brpc+protobuf
# closed schema (brpc_ps_server.cc).
# --------------------------------------------------------------------------

def _pack(obj, out: bytearray):
    if obj is None:
        out.append(0x00)
    elif obj is True:
        out.append(0x01)
    elif obj is False:
        out.append(0x02)
    elif isinstance(obj, int):
        out.append(0x03)
        out += struct.pack("<q", obj)
    elif isinstance(obj, float):
        out.append(0x04)
        out += struct.pack("<d", obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(0x05)
        out += struct.pack("<I", len(b)) + b
    elif isinstance(obj, (bytes, bytearray)):
        out.append(0x06)
        out += struct.pack("<Q", len(obj)) + obj
    elif isinstance(obj, (list, tuple)):
        out.append(0x07 if isinstance(obj, list) else 0x08)
        out += struct.pack("<I", len(obj))
        for item in obj:
            _pack(item, out)
    elif isinstance(obj, dict):
        out.append(0x09)
        out += struct.pack("<I", len(obj))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError(f"PS wire dict keys must be str, got {k!r}")
            kb = k.encode("utf-8")
            out += struct.pack("<I", len(kb)) + kb
            _pack(v, out)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("object arrays cannot cross the PS wire")
        arr = np.ascontiguousarray(obj)
        dt = arr.dtype.str.encode("ascii")
        out.append(0x0A)
        out += struct.pack("<B", len(dt)) + dt
        out += struct.pack("<B", arr.ndim)
        out += struct.pack(f"<{arr.ndim}q", *arr.shape)
        raw = arr.tobytes()
        out += struct.pack("<Q", len(raw)) + raw
    elif isinstance(obj, (np.integer,)):
        _pack(int(obj), out)
    elif isinstance(obj, (np.floating,)):
        _pack(float(obj), out)
    else:
        raise TypeError(f"type {type(obj).__name__} cannot cross the PS wire")


def _unpack(buf: memoryview, pos: int):
    tag = buf[pos]
    pos += 1
    if tag == 0x00:
        return None, pos
    if tag == 0x01:
        return True, pos
    if tag == 0x02:
        return False, pos
    if tag == 0x03:
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if tag == 0x04:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == 0x05:
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if tag == 0x06:
        (n,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        return bytes(buf[pos:pos + n]), pos + n
    if tag in (0x07, 0x08):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _unpack(buf, pos)
            items.append(item)
        return (items if tag == 0x07 else tuple(items)), pos
    if tag == 0x09:
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            (kn,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            k = bytes(buf[pos:pos + kn]).decode("utf-8")
            pos += kn
            d[k], pos = _unpack(buf, pos)
        return d, pos
    if tag == 0x0A:
        dn = buf[pos]
        pos += 1
        dt = np.dtype(bytes(buf[pos:pos + dn]).decode("ascii"))
        if dt.hasobject:
            raise TypeError("object arrays rejected on the PS wire")
        pos += dn
        ndim = buf[pos]
        pos += 1
        shape = struct.unpack_from(f"<{ndim}q", buf, pos)
        pos += 8 * ndim
        (raw_n,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        arr = np.frombuffer(buf[pos:pos + raw_n], dtype=dt).reshape(shape)
        return arr.copy(), pos + raw_n
    raise ValueError(f"bad PS wire tag 0x{tag:02x}")


def _send_msg(sock, obj):
    out = bytearray(8)
    _pack(obj, out)
    struct.pack_into("<Q", out, 0, len(out) - 8)
    sock.sendall(out)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    obj, _ = _unpack(memoryview(buf), 0)
    return obj


class DenseTable:
    """Server-side dense parameter block (reference:
    ps/table/memory_dense_table.cc — dense params with SGD/adam rules applied
    at the server). Host math is vectorized numpy; the TPU never sees these
    (dense training params live on-chip — this table serves the PS-mode
    workflows where the server owns them)."""

    def __init__(self, shape, opt="sgd", lr=0.05, momentum=0.9,
                 epsilon=1e-6, init_value=0.0):
        self.value = np.full(shape, float(init_value), np.float32)
        self.opt = opt
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self._slot = np.zeros(shape, np.float32)

    def pull(self):
        return self.value

    def push(self, grad, lr=-1.0):
        g = np.asarray(grad, np.float32).reshape(self.value.shape)
        eta = lr if lr > 0 else self.lr
        if self.opt == "adagrad":
            self._slot += g * g
            self.value -= eta * g / (np.sqrt(self._slot) + self.epsilon)
        elif self.opt == "momentum":
            self._slot = self.momentum * self._slot + g
            self.value -= eta * self._slot
        else:
            self.value -= eta * g

    def assign(self, value):
        self.value[...] = np.asarray(value, np.float32).reshape(
            self.value.shape)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: "PsServer" = self.server.ps_server  # type: ignore
        while True:
            msg = _recv_msg(self.request)
            if msg is None:
                return
            method, kwargs = msg
            try:
                payload = server.dispatch(method, kwargs)
                _send_msg(self.request, (True, payload))
            except Exception as e:  # fault isolation per request
                _send_msg(self.request, (False, repr(e)))
            if method == "stop":
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PsServer:
    """One PS shard process (BrpcPsServer analog)."""

    def __init__(self, host="127.0.0.1", port=0):
        self.tables: Dict[int, SparseTable] = {}
        self.dense_tables: Dict[int, DenseTable] = {}
        self.graph_tables: Dict[int, GraphTable] = {}
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.ps_server = self  # type: ignore
        self.host, self.port = self._srv.server_address
        self._thread = None
        self._barrier_count = {}
        self._barrier_cv = threading.Condition()

    # -- table ops (the downpour accessor surface) --------------------------
    def dispatch(self, method, kwargs):
        if method == "create_table":
            tid = int(kwargs.pop("table_id"))
            self.tables[tid] = SparseTable(**kwargs)
            return tid
        if method == "pull":
            t = self.tables[int(kwargs["table_id"])]
            return t.pull(np.asarray(kwargs["keys"], np.uint64),
                          kwargs.get("create_if_missing", True))
        if method == "push":
            t = self.tables[int(kwargs["table_id"])]
            t.push(np.asarray(kwargs["keys"], np.uint64), kwargs["grads"],
                   kwargs.get("lr", -1.0))
            return None
        if method == "assign":
            t = self.tables[int(kwargs["table_id"])]
            t.assign(np.asarray(kwargs["keys"], np.uint64), kwargs["values"])
            return None
        if method == "add":
            t = self.tables[int(kwargs["table_id"])]
            t.add(np.asarray(kwargs["keys"], np.uint64), kwargs["deltas"])
            return None
        if method == "size":
            return len(self.tables[int(kwargs["table_id"])])
        if method == "list_tables":
            return sorted(self.tables)
        if method == "save":
            tid = int(kwargs["table_id"])
            self.tables[tid].save(kwargs["path"])
            return None
        if method == "load":
            tid = int(kwargs["table_id"])
            self.tables[tid].load(kwargs["path"])
            return None
        if method == "shrink":
            t = self.tables[int(kwargs["table_id"])]
            return t.shrink(kwargs.get("decay", 0.98),
                            kwargs.get("threshold", 1.0))
        if method == "create_graph_table":
            tid = int(kwargs.pop("table_id"))
            self.graph_tables[tid] = GraphTable(**kwargs)
            return tid
        if method == "graph_add_edges":
            self.graph_tables[int(kwargs["table_id"])].add_edges(
                kwargs["src"], kwargs["dst"], kwargs.get("weights"),
                etype=kwargs.get("etype", ""))
            return None
        if method == "graph_set_features":
            self.graph_tables[int(kwargs["table_id"])].set_node_features(
                kwargs["ids"], kwargs["features"])
            return None
        if method == "graph_sample":
            t = self.graph_tables[int(kwargs["table_id"])]
            out, cnt = t.sample_neighbors(
                kwargs["ids"], int(kwargs["sample_size"]),
                weighted=bool(kwargs.get("weighted", False)),
                etype=kwargs.get("etype", ""))
            return [out, cnt]
        if method == "graph_features":
            t = self.graph_tables[int(kwargs["table_id"])]
            return t.get_node_features(kwargs["ids"])
        if method == "graph_degree":
            t = self.graph_tables[int(kwargs["table_id"])]
            return t.degree(kwargs["ids"], etype=kwargs.get("etype", ""))
        if method == "graph_list":
            t = self.graph_tables[int(kwargs["table_id"])]
            return t.pull_graph_list(int(kwargs["start"]),
                                     int(kwargs["size"]),
                                     etype=kwargs.get("etype", ""))
        if method == "graph_clear":
            self.graph_tables[int(kwargs["table_id"])].clear_nodes(
                kwargs.get("etype"))
            return None
        if method == "graph_save":
            self.graph_tables[int(kwargs["table_id"])].save(kwargs["path"])
            return None
        if method == "graph_load":
            self.graph_tables[int(kwargs["table_id"])].load(kwargs["path"])
            return None
        if method == "create_dense_table":
            tid = int(kwargs.pop("table_id"))
            self.dense_tables[tid] = DenseTable(
                tuple(kwargs.pop("shape")), **kwargs)
            return tid
        if method == "pull_dense":
            return self.dense_tables[int(kwargs["table_id"])].pull()
        if method == "push_dense":
            self.dense_tables[int(kwargs["table_id"])].push(
                kwargs["grad"], kwargs.get("lr", -1.0))
            return None
        if method == "assign_dense":
            self.dense_tables[int(kwargs["table_id"])].assign(kwargs["value"])
            return None
        if method == "barrier":
            return self._barrier(kwargs["group"], int(kwargs["n"]))
        if method == "ping":
            return "pong"
        if method == "stop":
            threading.Thread(target=self._srv.shutdown, daemon=True).start()
            return None
        raise ValueError(f"unknown PS method {method!r}")

    def _barrier(self, group, n):
        with self._barrier_cv:
            self._barrier_count[group] = self._barrier_count.get(group, 0) + 1
            if self._barrier_count[group] >= n:
                self._barrier_count[group] = 0
                self._barrier_cv.notify_all()
                return True
            self._barrier_cv.wait(timeout=60)
            return True

    # -- lifecycle ----------------------------------------------------------
    def start(self, background=True):
        if background:
            self._thread = threading.Thread(target=self._srv.serve_forever,
                                            daemon=True)
            self._thread.start()
        else:
            self._srv.serve_forever()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"


class PsClient:
    """Trainer-side client (BrpcPsClient analog). Keys are sharded across
    servers by hash, mirroring the reference's shard-by-key routing."""

    def __init__(self, endpoints):
        self.endpoints = list(endpoints)
        self._socks: Dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._tables: Dict[int, str] = {}  # id -> kind (created via this client)

    def _sock(self, i):
        with self._lock:
            s = self._socks.get(i)
            if s is None:
                host, port = self.endpoints[i].rsplit(":", 1)
                s = socket.create_connection((host, int(port)), timeout=60)
                self._socks[i] = s
            return s

    def _call(self, i, method, **kwargs):
        s = self._sock(i)
        with self._lock:
            _send_msg(s, (method, kwargs))
            reply = _recv_msg(s)
            if reply is None:  # clean EOF: server closed mid-handshake
                self._socks.pop(i, None)
                try:
                    s.close()
                except OSError:
                    pass
                raise ConnectionError(
                    f"PS server {self.endpoints[i]} closed the connection")
            ok, payload = reply
        if not ok:
            raise RuntimeError(f"PS rpc {method} failed: {payload}")
        return payload

    def _route(self, keys):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        n = len(self.endpoints)
        if n == 1:
            return [(0, np.arange(keys.size), keys)]
        # splitmix64-style mix → uniform over all servers for any n
        with np.errstate(over="ignore"):
            h = keys * np.uint64(0x9E3779B97F4A7C15)
            h ^= h >> np.uint64(30)
            h = h * np.uint64(0xBF58476D1CE4E5B9)
            h ^= h >> np.uint64(31)
        shard = h % np.uint64(n)
        out = []
        for i in range(n):
            idx = np.nonzero(shard == i)[0]
            if idx.size:
                out.append((i, idx, keys[idx]))
        return out

    def create_table(self, table_id, dim, **kw):
        for i in range(len(self.endpoints)):
            self._call(i, "create_table", table_id=table_id, dim=dim, **kw)
        self._tables[int(table_id)] = "sparse"

    def table_ids(self):
        """Union of sparse table ids across all shards — the SERVER'S
        view, so tables created by other clients are covered too."""
        ids = set(self._tables)
        for i in range(len(self.endpoints)):
            ids.update(int(t) for t in self._call(i, "list_tables"))
        return sorted(ids)

    def shrink(self, table_id, decay=0.98, threshold=1.0):
        """Decay show counts and drop cold rows on every shard
        (fleet_wrapper.cc ShrinkSparseTable)."""
        dropped = 0
        for i in range(len(self.endpoints)):
            dropped += int(self._call(i, "shrink", table_id=table_id,
                                      decay=decay, threshold=threshold) or 0)
        return dropped

    def pull(self, table_id, keys, create_if_missing=True):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        dim = None
        out = None
        for i, idx, sub in self._route(keys):
            rows = self._call(i, "pull", table_id=table_id, keys=sub,
                              create_if_missing=create_if_missing)
            if out is None:
                dim = rows.shape[1]
                out = np.empty((keys.size, dim), np.float32)
            out[idx] = rows
        return out if out is not None else np.empty((0, 0), np.float32)

    def push(self, table_id, keys, grads, lr=-1.0):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        for i, idx, sub in self._route(keys):
            self._call(i, "push", table_id=table_id, keys=sub,
                       grads=grads[idx], lr=lr)

    def assign(self, table_id, keys, values):
        keys = np.asarray(keys, np.uint64).reshape(-1)
        values = np.asarray(values, np.float32).reshape(keys.size, -1)
        for i, idx, sub in self._route(keys):
            self._call(i, "assign", table_id=table_id, keys=sub,
                       values=values[idx])

    def add(self, table_id, keys, deltas):
        """Server-side atomic += (geo delta merge — no lost updates)."""
        keys = np.asarray(keys, np.uint64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(keys.size, -1)
        for i, idx, sub in self._route(keys):
            self._call(i, "add", table_id=table_id, keys=sub,
                       deltas=deltas[idx])

    # dense tables live whole on one server: table_id % n_servers (the
    # reference block-shards large dense params; whole-table placement is the
    # simple correct policy at this scale)
    def _dense_server(self, table_id):
        return int(table_id) % len(self.endpoints)

    def create_dense_table(self, table_id, shape, **kw):
        self._call(self._dense_server(table_id), "create_dense_table",
                   table_id=table_id, shape=list(shape), **kw)

    def pull_dense(self, table_id):
        return self._call(self._dense_server(table_id), "pull_dense",
                          table_id=table_id)

    def push_dense(self, table_id, grad, lr=-1.0):
        self._call(self._dense_server(table_id), "push_dense",
                   table_id=table_id, grad=np.asarray(grad, np.float32),
                   lr=lr)

    def assign_dense(self, table_id, value):
        self._call(self._dense_server(table_id), "assign_dense",
                   table_id=table_id, value=np.asarray(value, np.float32))

    def table_size(self, table_id):
        return sum(self._call(i, "size", table_id=table_id)
                   for i in range(len(self.endpoints)))

    def save(self, table_id, path):
        for i in range(len(self.endpoints)):
            self._call(i, "save", table_id=table_id,
                       path=f"{path}.shard{i}")

    def load(self, table_id, path):
        for i in range(len(self.endpoints)):
            self._call(i, "load", table_id=table_id,
                       path=f"{path}.shard{i}")

    # -- graph table (common_graph_table.cc surface, sharded by node id) ----
    def create_graph_table(self, table_id, **kw):
        for i in range(len(self.endpoints)):
            self._call(i, "create_graph_table", table_id=table_id, **kw)

    def graph_add_edges(self, table_id, src, dst, weights=None, etype=""):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = (np.asarray(weights, np.float32).reshape(-1)
             if weights is not None else None)
        for i, idx, _ in self._route(src.astype(np.uint64)):
            self._call(i, "graph_add_edges", table_id=table_id,
                       src=src[idx], dst=dst[idx],
                       weights=None if w is None else w[idx], etype=etype)

    def graph_sample_neighbors(self, table_id, ids, sample_size,
                               weighted=False, etype=""):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((ids.size, int(sample_size)), -1, np.int64)
        cnt = np.zeros(ids.size, np.int64)
        for i, idx, _ in self._route(ids.astype(np.uint64)):
            o, c = self._call(i, "graph_sample", table_id=table_id,
                              ids=ids[idx], sample_size=sample_size,
                              weighted=weighted, etype=etype)
            out[idx], cnt[idx] = o, c
        return out, cnt

    def graph_node_features(self, table_id, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = None
        for i, idx, _ in self._route(ids.astype(np.uint64)):
            rows = self._call(i, "graph_features", table_id=table_id,
                              ids=ids[idx])
            if out is None:
                out = np.zeros((ids.size, rows.shape[1]), np.float32)
            out[idx] = rows
        return out if out is not None else np.empty((0, 0), np.float32)

    def graph_degree(self, table_id, ids, etype=""):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros(ids.size, np.int64)
        for i, idx, _ in self._route(ids.astype(np.uint64)):
            out[idx] = self._call(i, "graph_degree", table_id=table_id,
                                  ids=ids[idx], etype=etype)
        return out

    def graph_pull_list(self, table_id, start, size, etype=""):
        """Paginated global node listing: merges each shard's prefix, so a
        page at offset k refetches O(k) ids — fine for peeks; full-graph
        epoch scans should use graph_node_iter (O(N) total)."""
        pages = [self._call(i, "graph_list", table_id=table_id, start=0,
                            size=int(start) + int(size), etype=etype)
                 for i in range(len(self.endpoints))]
        merged = np.sort(np.concatenate(pages)) if pages else \
            np.empty(0, np.int64)
        return merged[int(start):int(start) + int(size)]

    def graph_node_iter(self, table_id, batch, etype=""):
        """Yield sorted node-id batches over the whole sharded graph with
        per-shard cursors — each id crosses the wire exactly once (the
        full-graph GNN epoch scan, linear unlike repeated graph_pull_list)."""
        n = len(self.endpoints)
        cursors = [0] * n
        buffers = [np.empty(0, np.int64) for _ in range(n)]
        done = [False] * n
        batch = int(batch)
        out = np.empty(0, np.int64)
        while True:
            for i in range(n):
                if buffers[i].size == 0 and not done[i]:
                    page = self._call(i, "graph_list", table_id=table_id,
                                      start=cursors[i], size=batch,
                                      etype=etype)
                    cursors[i] += len(page)
                    done[i] = len(page) < batch
                    buffers[i] = np.asarray(page, np.int64)
            # safe to emit everything <= the smallest refillable frontier
            frontiers = [b[-1] for i, b in enumerate(buffers)
                         if b.size and not done[i]]
            merged = np.sort(np.concatenate(
                [b for b in buffers if b.size] + [out]))
            if frontiers:
                cut = int(np.searchsorted(merged, min(frontiers),
                                          side="right"))
            else:
                cut = merged.size
            emit, out = merged[:cut], merged[cut:]
            buffers = [np.empty(0, np.int64) for _ in range(n)]
            for s in range(0, emit.size - emit.size % batch, batch):
                yield emit[s:s + batch]
            tail = emit[emit.size - emit.size % batch:]
            out = np.sort(np.concatenate([tail, out]))
            if all(done) and not any(b.size for b in buffers):
                for s in range(0, out.size, batch):
                    yield out[s:s + batch]
                return

    def graph_clear(self, table_id, etype=None):
        for i in range(len(self.endpoints)):
            self._call(i, "graph_clear", table_id=table_id, etype=etype)

    def graph_save(self, table_id, path):
        for i in range(len(self.endpoints)):
            self._call(i, "graph_save", table_id=table_id,
                       path=f"{path}.shard{i}")

    def graph_load(self, table_id, path):
        for i in range(len(self.endpoints)):
            self._call(i, "graph_load", table_id=table_id,
                       path=f"{path}.shard{i}")

    def graph_random_walk(self, table_id, start_ids, walk_len, etype=""):
        """Walks stepped client-side (each hop routes to the shard owning
        the current node — the walk naturally crosses servers)."""
        cur = np.asarray(start_ids, np.int64).reshape(-1)
        walks = np.full((cur.size, int(walk_len) + 1), -1, np.int64)
        walks[:, 0] = cur
        alive = cur >= 0
        for step in range(1, int(walk_len) + 1):
            if not alive.any():
                break
            nxt, cnt = self.graph_sample_neighbors(
                table_id, cur[alive], 1, etype=etype)
            step_ids = np.full(cur.size, -1, np.int64)
            step_ids[alive] = nxt[:, 0]
            walks[:, step] = step_ids
            cur = step_ids
            alive = cur >= 0
        return walks

    def graph_meta_path_walk(self, table_id, start_ids, meta_path):
        cur = np.asarray(start_ids, np.int64).reshape(-1)
        walks = np.full((cur.size, len(meta_path) + 1), -1, np.int64)
        walks[:, 0] = cur
        alive = cur >= 0
        for step, et in enumerate(meta_path, start=1):
            if not alive.any():
                break
            nxt, _ = self.graph_sample_neighbors(
                table_id, cur[alive], 1, etype=et)
            step_ids = np.full(cur.size, -1, np.int64)
            step_ids[alive] = nxt[:, 0]
            walks[:, step] = step_ids
            cur = step_ids
            alive = cur >= 0
        return walks

    def barrier(self, group="worker", n=1):
        self._call(0, "barrier", group=group, n=n)

    def stop_all(self):
        for i in range(len(self.endpoints)):
            try:
                self._call(i, "stop")
            except (OSError, EOFError, RuntimeError) as e:
                # best-effort fan-out: a server that already died is fine,
                # but the failed stop is recorded (rule C003)
                from ...observability.events import get_event_log
                get_event_log().debug(
                    "ps", "stop RPC failed (server already down?)",
                    endpoint=str(self.endpoints[i]), error=repr(e))

    def close(self):
        with self._lock:
            for s in self._socks.values():
                s.close()
            self._socks.clear()


class LocalPs:
    """In-process pseudo client over local tables (single-machine mode —
    what the reference calls `local` PS)."""

    def __init__(self):
        self.tables: Dict[int, SparseTable] = {}
        self.dense_tables: Dict[int, DenseTable] = {}
        self.graph_tables: Dict[int, GraphTable] = {}

    def create_table(self, table_id, dim, **kw):
        self.tables[int(table_id)] = SparseTable(dim=dim, **kw)

    def create_dense_table(self, table_id, shape, **kw):
        self.dense_tables[int(table_id)] = DenseTable(tuple(shape), **kw)

    def pull_dense(self, table_id):
        return self.dense_tables[int(table_id)].pull()

    def push_dense(self, table_id, grad, lr=-1.0):
        self.dense_tables[int(table_id)].push(grad, lr)

    def assign_dense(self, table_id, value):
        self.dense_tables[int(table_id)].assign(value)

    def pull(self, table_id, keys, create_if_missing=True):
        return self.tables[int(table_id)].pull(keys, create_if_missing)

    def push(self, table_id, keys, grads, lr=-1.0):
        self.tables[int(table_id)].push(keys, grads, lr)

    def assign(self, table_id, keys, values):
        self.tables[int(table_id)].assign(keys, values)

    def add(self, table_id, keys, deltas):
        self.tables[int(table_id)].add(keys, deltas)

    def table_size(self, table_id):
        return len(self.tables[int(table_id)])

    def save(self, table_id, path):
        self.tables[int(table_id)].save(path)

    def load(self, table_id, path):
        self.tables[int(table_id)].load(path)

    def shrink(self, table_id, decay=0.98, threshold=1.0):
        return self.tables[int(table_id)].shrink(decay, threshold)

    # -- graph table: same surface as PsClient, served in-process ----------
    def create_graph_table(self, table_id, **kw):
        self.graph_tables[int(table_id)] = GraphTable(**kw)

    def _gt(self, table_id):
        return self.graph_tables[int(table_id)]

    def graph_add_edges(self, table_id, src, dst, weights=None, etype=""):
        self._gt(table_id).add_edges(src, dst, weights, etype=etype)

    def graph_sample_neighbors(self, table_id, ids, sample_size,
                               weighted=False, etype=""):
        return self._gt(table_id).sample_neighbors(
            ids, sample_size, weighted=weighted, etype=etype)

    def graph_node_features(self, table_id, ids):
        return self._gt(table_id).get_node_features(ids)

    def graph_degree(self, table_id, ids, etype=""):
        return self._gt(table_id).degree(ids, etype=etype)

    def graph_pull_list(self, table_id, start, size, etype=""):
        return self._gt(table_id).pull_graph_list(start, size, etype=etype)

    def graph_random_walk(self, table_id, start_ids, walk_len, etype=""):
        return self._gt(table_id).random_walk(start_ids, walk_len,
                                              etype=etype)

    def graph_meta_path_walk(self, table_id, start_ids, meta_path):
        return self._gt(table_id).meta_path_walk(start_ids, meta_path)

    def graph_node_iter(self, table_id, batch, etype=""):
        start = 0
        while True:
            page = self._gt(table_id).pull_graph_list(start, int(batch),
                                                      etype=etype)
            if page.size == 0:
                return
            yield page
            start += page.size

    def graph_clear(self, table_id, etype=None):
        self._gt(table_id).clear_nodes(etype)

    def graph_save(self, table_id, path):
        self._gt(table_id).save(path)

    def graph_load(self, table_id, path):
        self._gt(table_id).load(path)

    def barrier(self, group="worker", n=1):
        pass

    def stop_all(self):
        pass


class TheOnePSRuntime:
    """Runtime facade (distributed/ps/the_one_ps.py analog): owns the
    server/client lifecycle driven by fleet.init_server/init_worker."""

    _current: Optional["TheOnePSRuntime"] = None

    def __init__(self, role_maker=None):
        self.role_maker = role_maker
        self.server: Optional[PsServer] = None
        self.client = None
        self.communicator = None  # async/geo trainer-side comm (communicator.py)
        TheOnePSRuntime._current = self

    @classmethod
    def current(cls):
        if cls._current is None:
            cls._current = TheOnePSRuntime()
            cls._current.client = LocalPs()
        return cls._current

    # server side -----------------------------------------------------------
    def init_server(self, host="127.0.0.1", port=0):
        self.server = PsServer(host, port).start()
        return self.server.endpoint

    def run_server(self):
        """Blocks serving requests until stop() — the reference's run_server
        semantics (a server-role script parks here)."""
        if self.server is None:
            self.init_server()
        if self.server._thread is not None:
            self.server._thread.join()  # park until shutdown
        return self.server

    # worker side -----------------------------------------------------------
    def init_worker(self, server_endpoints=None, strategy=None):
        eps = server_endpoints or [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        self.client = PsClient(eps) if eps else LocalPs()
        from .communicator import Communicator

        self.communicator = Communicator.create(self.client, strategy)
        self.communicator.start()
        return self.client

    def comm(self):
        """Active communicator (sync passthrough if init_worker not called)."""
        if self.communicator is None:
            from .communicator import Communicator

            self.communicator = Communicator(self.client or LocalPs())
            if self.client is None:
                self.client = self.communicator.client
            self.communicator.start()
        return self.communicator

    def stop_worker(self):
        if self.communicator is not None:
            self.communicator.stop()
        if isinstance(self.client, PsClient):
            self.client.close()


# --------------------------------------------------------------------------
# lookup op with PS-backed gradient (operators/pscore/distributed_lookup_table)
# --------------------------------------------------------------------------

def distributed_lookup_table(ids, table_id=0, client=None, lr=-1.0):
    """Pull embedding rows for `ids`; backward pushes row gradients to the
    table (the reference's distributed_lookup_table + push_sparse pair).

    Host-side op: runs eagerly around the XLA program (the reference likewise
    keeps sparse pull/push outside the dense graph).
    """
    import jax
    import jax.numpy as jnp

    from ...framework import autograd
    from ...framework.tensor import Tensor

    comm = (None if client is not None else TheOnePSRuntime.current().comm())
    if client is None:
        client = comm.client
    ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
    flat = ids_np.reshape(-1).astype(np.uint64)
    rows = comm.pull_sparse(table_id, flat) if comm is not None \
        else client.pull(table_id, flat)
    dim = rows.shape[1]
    out_val = jnp.asarray(rows.reshape(ids_np.shape + (dim,)))

    out = Tensor(out_val, _internal=True)
    if autograd.is_grad_enabled():
        def vjp_fn(cot):
            g = np.asarray(cot).reshape(-1, dim)
            if comm is not None:
                comm.push_sparse(table_id, flat, g, lr=lr)
            else:
                client.push(table_id, flat, g, lr=lr)
            return []

        node = autograd.GradNode(
            vjp_fn, [], [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
            multi_output=False, name="distributed_lookup_table")
        out.stop_gradient = False
        out._grad_node = node
        out._out_index = 0
    return out


def distributed_push_sparse(ids, grads, table_id=0, client=None, lr=-1.0):
    client = client or TheOnePSRuntime.current().client
    from ...framework.tensor import Tensor

    ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
    g_np = np.asarray(grads.numpy() if isinstance(grads, Tensor) else grads)
    client.push(table_id, ids_np.reshape(-1).astype(np.uint64),
                g_np.reshape(ids_np.size, -1), lr=lr)

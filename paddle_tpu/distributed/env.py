"""Distributed environment (reference: the PADDLE_TRAINER_* env protocol
assembled by fleet/launch_utils.py, read by fleet/base/role_maker.py).

TPU-native: rank/world come from jax.distributed (multi-host) or the launch
env; a single process over a local mesh is world_size == number of mesh data
shards from the model's perspective, but the *process* rank/world below mirror
the reference's trainer-process semantics.
"""
from __future__ import annotations

import os

import jax

_initialized = [False]


def get_rank() -> int:
    if _initialized[0]:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size() -> int:
    if _initialized[0]:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))


def init_parallel_env():
    """paddle.distributed.init_parallel_env (distributed/parallel.py:79).

    Reference: NCCL id TCP rendezvous (gen_comm_id_helper.cc:343) + comm init.
    TPU-native: jax.distributed.initialize — the PJRT coordination service is
    the rendezvous; XLA owns the communicators.
    """
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))
    if nproc > 1 and not _initialized[0]:
        if coord and ":" not in coord:
            coord = f"{coord}:{os.environ.get('MASTER_PORT', '8476')}"
        timeout = int(os.environ.get("PADDLE_RENDEZVOUS_TIMEOUT", "300"))
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=pid,
            initialization_timeout=timeout,
        )
        _initialized[0] = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized[0]


class ParallelEnv:
    """paddle.distributed.ParallelEnv facade."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def dev_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0"))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]

"""SPMD pipeline parallelism — the real micro-batch schedule.

Reference capability: 1F1B with micro-batch overlap
(fleet/meta_parallel/pipeline_parallel.py:80-150 interleaving fwd/bwd,
pp_utils/p2p_communication.py:216-434 p2p send/recv between stage ranks,
static-graph SectionWorker paddle/fluid/framework/section_worker.cc:143-199).

TPU-native redesign — a collective-permute pipeline inside ONE SPMD program:

- every pipe rank holds its stage's parameter slice (leading stacked-layer dim
  sharded over the 'pipe' mesh axis);
- micro-batches rotate through the stages with lax.ppermute: at step t, stage
  s computes micro-batch (t - s) — all stages busy in steady state, the same
  concurrency 1F1B achieves with p2p ranks;
- the loop runs M + P - 1 steps (bubble fraction (P-1)/(M+P-1), identical to
  GPipe fill/drain), with XLA overlapping each ppermute with the next step's
  compute (ICI transfer hides behind MXU work);
- backward is the TRANSPOSED pipeline: jax AD differentiates through scan +
  ppermute, yielding the reverse schedule for free — the part the reference
  spends p2p_communication.py hand-coding;
- inside the manual region tensor parallelism is explicit Megatron
  (column/row-sharded matmuls + psum over 'model') and sequence parallelism
  is the ring-attention body over 'sep' — the composition the reference
  builds from three separate communicator rings.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_spmd(
    stage_fn: Callable,
    params,
    x,
    *,
    mesh,
    param_specs,
    pipe_axis: str = "pipe",
    microbatches: Optional[int] = None,
    batch_axes: Sequence[str] = ("data", "sharding"),
    seq_axis: str = "sep",
):
    """Run `x` through a pipeline of P = mesh.shape[pipe_axis] stages.

    stage_fn(local_params, x_mb) -> y_mb applies ONE stage's layers (the
    caller scans its local layer slices). `params` is a tuple of stacked
    arrays whose leading dim is sharded over `pipe_axis` (param_specs gives
    each one's full PartitionSpec INCLUDING the leading pipe dim). x is the
    full global batch [b, ...]; it is split into `microbatches` equal
    micro-batches along dim 0 (default: the pipe degree, the minimum that
    fills the pipeline).
    """
    P_deg = int(mesh.shape[pipe_axis])
    M = int(microbatches or P_deg)
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} micro-batches")
    mb = b // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    batch_tuple = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    seq = seq_axis if seq_axis in mesh.axis_names else None
    # [M, mb, s, ...]: micro dim unsharded, batch over dp axes, seq over sp
    x_spec = P(None, batch_tuple, seq, *([None] * (x.ndim - 2)))

    def body(params_local, xl):
        stage = jax.lax.axis_index(pipe_axis)
        T = M + P_deg - 1
        perm = [(i, (i + 1) % P_deg) for i in range(P_deg)]
        state0 = jnp.zeros(xl.shape[1:], xl.dtype)
        out0 = jnp.zeros_like(xl)

        def step(carry, t):
            state, outs = carry
            # fill: stage 0 ingests micro-batch t (clipped during drain)
            fresh = jax.lax.dynamic_index_in_dim(
                xl, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            state = jnp.where(stage == 0, fresh, state)
            y = stage_fn(params_local, state)
            # drain: micro-batch (t - P + 1) leaves the last stage at step t
            oi = t - (P_deg - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(oi, 0, M - 1), 0)
            outs = jnp.where(oi >= 0, upd, outs)
            # hand-off: stage s -> s+1 (wrap to 0 is overwritten by ingest)
            state = jax.lax.ppermute(y, pipe_axis, perm)
            return (state, outs), None

        (_, outs), _ = jax.lax.scan(step, (state0, out0), jnp.arange(T))
        # results live on the last stage; replicate over the pipe axis so the
        # (SPMD-replicated) head/loss can proceed on every rank
        outs = jnp.where(stage == P_deg - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, pipe_axis)

    out_mb = jax.shard_map(
        body, mesh=mesh, in_specs=(tuple(param_specs), x_spec),
        out_specs=x_spec, check_vma=False,
    )(tuple(params), x_mb)
    return out_mb.reshape(b, *x.shape[1:])

"""Hybrid-parallel topology (reference: fleet/base/topology.py:36,117 —
CommunicateTopology + HybridCommunicateGroup carving NCCL subgroups from a 4-D
process grid, order ["data","pipe","sharding","model"]).

TPU-native: the grid IS the jax Mesh (plus net-new "sep" for sequence
parallelism). "Rank" is this device's mesh coordinate in single-process SPMD
(coordinate of device 0 for host-level queries) or the process coordinate in
multi-host. Groups are axis views — no subgroup-creation cost; XLA partitions
communicators from sharding specs.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ... import mesh as mesh_mod
from ...collective import Group, new_group
from ...env import get_rank


class CommunicateTopology:
    """reference: topology.py:36."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(
            hybrid_group_names or ["data", "pipe", "sharding", "sep", "model"]
        )
        self._dims = list(dims or [1] * len(self._parallel_names))
        self._world = np.arange(int(np.prod(self._dims))).reshape(self._dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(self._world.size)

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._parallel_names]
        return int(self._world[tuple(coords)])

    def get_coord(self, rank):
        coords = np.unravel_index(rank, self._dims)
        return dict(zip(self._parallel_names, (int(c) for c in coords)))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[axis] = index
        return self._world[tuple(sl)].reshape(-1).tolist()

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank-lists."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._world, axis, -1).reshape(-1, self._dims[axis])
        return moved.tolist()


class HybridCommunicateGroup:
    """reference: topology.py:117. Degrees map onto mesh axes
    {data,pipe,sharding,sep,model}."""

    def __init__(self, topology: CommunicateTopology = None, dp=1, mp=1, pp=1,
                 sharding=1, sep=1):
        if topology is not None:
            self._topo = topology
            get = topology.get_dim
            dp, pp, sharding = get("data"), get("pipe"), get("sharding")
            mp = get("model")
            sep = get("sep") if "sep" in topology.get_hybrid_group_names() else 1
        else:
            self._topo = CommunicateTopology(
                ["data", "pipe", "sharding", "sep", "model"],
                [dp, pp, sharding, sep, mp],
            )
        self._dp_degree = dp
        self._mp_degree = mp
        self._pp_degree = pp
        self._sharding_degree = sharding
        self._sep_degree = sep
        self.global_rank = get_rank()
        self._coord = self._topo.get_coord(
            self.global_rank % self._topo.world_size()
        )
        # axis-view groups
        self._dp_group = new_group(axes=("data",))
        self._mp_group = new_group(axes=("model",))
        self._pp_group = new_group(axes=("pipe",))
        self._sharding_group = new_group(axes=("sharding",))
        self._sep_group = new_group(axes=("sep",))
        self._check_group = new_group(axes=("data", "pipe", "sharding", "sep", "model"))

    def __repr__(self):
        return (f"HybridCommunicateGroup(dp={self._dp_degree}, mp={self._mp_degree}, "
                f"pp={self._pp_degree}, sharding={self._sharding_degree}, "
                f"sep={self._sep_degree})")

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "tensor_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_rank(self):
        return self._coord["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sequence (net-new)
    def get_sep_parallel_rank(self):
        return self._coord["sep"]

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = dict(self._coord)
        coord["pipe"] = stage_id
        coord.update(kwargs)
        return self._topo.get_rank(**coord)

from .distributed_strategy import DistributedStrategy  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401

"""DistributedStrategy (reference: framework/distributed_strategy.proto:271 —
38 toggles + config submessages; python facade
fleet/base/distributed_strategy.py with check_configs_key validation).

The keys keep their reference names; on TPU they select partition specs and
compiled-step behavior instead of program rewrites.
"""
from __future__ import annotations

import copy

_DEFAULTS = {
    # comm/overlap knobs (moot under XLA, accepted for compat)
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "nccl_comm_num": 1,
    "sync_nccl_allreduce": True,
    # execution
    "recompute": False,
    "recompute_configs": {"checkpoints": [], "enable_offload": False},
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "1F1B"},
    "amp": False,
    "amp_configs": {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                    "use_fp16_guard": True, "custom_white_list": [],
                    "custom_black_list": []},
    "bf16": True,
    "sharding": False,
    "sharding_configs": {"sharding_degree": 1, "stage": 1, "mp_degree": 1,
                         "dp_degree": 1, "pp_degree": 1,
                         "segment_broadcast_MB": 32, "offload": False},
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1},
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd": False,
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1},
    "asp": False,
    "fp16_allreduce": False,
    # bucketed/quantized gradient communication (distributed/grad_comm.py):
    # codec one of fp32/bf16/int8/int8_block/fp8_block; buffer sizes in MB
    # mirror the reference DataParallel kwargs; error_feedback carries the
    # quantization residual across steps (int8 + the blockwise codecs);
    # overlap launches each bucket's collective the moment backward
    # finishes producing it (distributed/overlap.py) — bit-identical to
    # serial sync, comm time hidden under backward; block_size is the
    # elements-per-abs-max-scale granularity of the blockwise codecs
    # (EQuARX; also honored in-trace by jit.TrainStep(grad_comm=) through
    # hapi's fused step)
    "grad_comm": False,
    "grad_comm_configs": {"codec": "bf16", "comm_buffer_size_MB": 25,
                          "last_comm_buffer_size_MB": 1,
                          "error_feedback": True,
                          "overlap": False,
                          "block_size": 1024},
    # distributed telemetry plane (observability/, ISSUE 6): cross-rank
    # metric aggregation cadence, per-rank exposition endpoint, and
    # flight-recorder depth. http_port 0 inherits FLAGS_telemetry_http_port
    # (0 there too = off); aggregate_every_n_steps 0 = aggregate only at
    # dump time (MetricsCallback freq)
    "telemetry": False,
    "telemetry_configs": {"aggregate_every_n_steps": 0,
                          "http_port": 0,
                          "flight_recorder_capacity": 4096},
    "semi_auto": False,
    "auto_search": False,
    "heter_ccl_mode": False,
    "find_unused_parameters": False,
    "last_comm_group_size_MB": 1,
    "without_graph_optimization": False,
    # hybrid topology degrees (fleet_base.py:363)
    "hybrid_configs": {
        "dp_degree": -1,
        "mp_degree": 1,
        "pp_degree": 1,
        "sharding_degree": 1,
        "sep_degree": 1,
        "mp_configs": {},
        "pp_configs": {},
    },
}


# compat knobs with no behavior here — setting them warns once instead of
# silently doing nothing (VERDICT r2 weak #5)
_INERT_BITS = {
    "semi_auto": "GSPMD auto-sharding always runs; there is no separate "
                 "semi-auto planner to enable",
    "auto_search": "mesh search lives in paddle_tpu.distributed."
                   "auto_parallel.planner.plan (AOT-compiled cost ranking "
                   "with the TPU compiler); fleet.init cannot search "
                   "before the model exists",
    "heter_ccl_mode": "heterogeneous collectives dissolve into the XLA "
                      "mesh; role wiring in fleet.heter covers the PS path",
    "nccl_comm_num": "NCCL communicator/stream counts have no XLA analog "
                     "— the compiler schedules collectives",
    "sync_nccl_allreduce": "XLA orders collectives; there is no async "
                           "NCCL stream to synchronize",
    "without_graph_optimization": "XLA always optimizes the whole "
                                  "program; there is no pass pipeline "
                                  "to bypass",
    "adaptive_localsgd": "loss-adaptive k is not implemented; "
                         "strategy.localsgd with localsgd_configs "
                         "k_steps gives fixed-interval LocalSGD",
}
_warned_inert: set = set()


class DistributedStrategy:
    def __init__(self):
        self._conf = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        conf = object.__getattribute__(self, "_conf")
        if name in conf:
            return conf[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        if name == "_conf":
            object.__setattr__(self, name, value)
            return
        if name not in self._conf:
            raise ValueError(
                f"Unknown DistributedStrategy field {name!r} "
                f"(reference: distributed_strategy.proto)"
            )
        if name in _INERT_BITS and value != _DEFAULTS[name]:
            from ....utils.compat import warn_compat_once

            warn_compat_once(_warned_inert, "DistributedStrategy.", name,
                             _INERT_BITS[name], stacklevel=3)
        if name.endswith("_configs") and isinstance(self._conf[name], dict):
            # check_configs_key semantics: unknown sub-keys rejected
            cur = self._conf[name]
            for k in value:
                if k not in cur:
                    raise ValueError(f"Unknown key {k!r} for {name}")
            cur.update(value)
        else:
            self._conf[name] = value

    def to_dict(self):
        return copy.deepcopy(self._conf)

    def __repr__(self):
        on = [k for k, v in self._conf.items() if v is True]
        return f"DistributedStrategy(enabled={on})"

"""Role makers (reference: fleet/base/role_maker.py, 1,140 LoC —
PaddleCloudRoleMaker reads the PADDLE_TRAINER_* env protocol; UserDefined
takes explicit args)."""
from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class PaddleCloudRoleMaker:
    """Derive role/rank/world from the launch env protocol."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._role = Role.WORKER
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e]
        self._heter_endpoints = [
            e for e in os.environ.get("PADDLE_HETER_TRAINER_IP_PORT_LIST",
                                      "").split(",") if e]
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        if training_role == "PSERVER":
            self._role = Role.SERVER
            self._current_id = int(os.environ.get("PADDLE_PSERVER_ID", 0))
        elif training_role == "HETER_TRAINER":
            # heterogeneous PS (reference: heter_client/heter_server.cc +
            # role_maker _heter_worker): device workers paired with CPU
            # trainers; dense compute here, sparse tables stay on the PS
            self._role = Role.HETER_WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        else:
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_heter_worker(self):
        return self._role == Role.HETER_WORKER

    def heter_worker_num(self):
        return len(self._heter_endpoints)

    def get_heter_worker_endpoints(self):
        return self._heter_endpoints

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id if self.is_server() else -1

    def worker_num(self):
        return self._trainers_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def role_id(self):
        return self._current_id

    def to_string(self):
        return (f"role={self._role} id={self._current_id} "
                f"workers={self._trainers_num} "
                f"servers={len(self._server_endpoints)}")


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=False, current_id=0, role=Role.WORKER,
                 worker_num=1, server_endpoints=None, heter_endpoints=None,
                 **kwargs):
        self._is_collective = is_collective
        self._role = role
        self._current_id = current_id
        self._trainers_num = worker_num
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = []
        self._heter_endpoints = heter_endpoints or []

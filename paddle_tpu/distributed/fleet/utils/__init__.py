"""fleet.utils — recompute + filesystem helpers.

Reference: fleet/utils/recompute.py:63,183 (RecomputeFunction PyLayer) and
fleet/utils/fs.py:119 (LocalFS/HDFSClient).
"""
from __future__ import annotations

import os
import shutil

import jax
import jax.numpy as jnp

from ....framework import autograd, random as rng_mod
from ....framework.tensor import Tensor

__all__ = ["recompute", "LocalFS", "HDFSClient"]


def recompute(function, *args, **kwargs):
    """Rematerialized call: forward runs WITHOUT taping (no residuals held);
    backward reruns `function` under grad to rebuild the sub-tape and pull
    gradients through it.

    The eager analog of jax.checkpoint — under jit/to_static tracing both
    passes land in one XLA program and XLA dedups what it can; eagerly it
    trades ~2x layer FLOPs for dropping all intermediate activations, same as
    the reference's RecomputeFunction (fleet/utils/recompute.py:63).
    """
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)  # API compat

    if not autograd.is_grad_enabled():
        return function(*args, **kwargs)

    gen = rng_mod.default_generator()
    rng_state = gen.get_state() if preserve_rng else None

    with autograd.no_grad():
        outs = function(*args, **kwargs)

    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    out_tensors = [o for o in out_list if isinstance(o, Tensor)]
    if not out_tensors:
        return outs

    diff_inputs = [a for a in args
                   if isinstance(a, Tensor) and not a.stop_gradient
                   and jnp.issubdtype(a._value.dtype, jnp.floating)]

    out_avals = [jax.ShapeDtypeStruct(o._value.shape, o._value.dtype)
                 for o in out_tensors]

    def vjp_fn(cots):
        cot_list = list(cots) if isinstance(cots, tuple) else [cots]
        if preserve_rng:
            saved = gen.get_state()
            gen.set_state(rng_state)
        try:
            # detached clones keep leaf-ness so we can collect their grads
            re_args = []
            detached = []
            for a in args:
                if isinstance(a, Tensor):
                    d = a.detach()
                    d.stop_gradient = a.stop_gradient
                    re_args.append(d)
                    if (not a.stop_gradient
                            and jnp.issubdtype(a._value.dtype, jnp.floating)):
                        detached.append(d)
                else:
                    re_args.append(a)
            re_outs = function(*re_args, **kwargs)
            re_list = (list(re_outs) if isinstance(re_outs, (tuple, list))
                       else [re_outs])
            re_tensors = [o for o in re_list if isinstance(o, Tensor)]
            grads = autograd.run_backward(
                re_tensors, grad_tensors=cot_list, collect=detached,
                accumulate=True)  # params inside `function` accumulate .grad
        finally:
            if preserve_rng:
                gen.set_state(saved)
        out = []
        for g in grads:
            out.append(g._value if g is not None else None)
        return out

    node = autograd.GradNode(
        vjp_fn,
        [(t, t._grad_node, t._out_index) for t in diff_inputs],
        out_avals,
        multi_output=len(out_tensors) > 1,
        name="recompute",
    )
    for i, o in enumerate(out_tensors):
        if jnp.issubdtype(o._value.dtype, jnp.floating):
            o.stop_gradient = False
            o._grad_node = node
            o._out_index = i
    return outs


class LocalFS:
    """Local filesystem client (fleet/utils/fs.py:119)."""

    def ls_dir(self, fs_path):
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """HDFS client stub — no hadoop runtime in this environment; the auto-
    checkpoint path accepts any object with the LocalFS interface."""

    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(
            "no hadoop runtime available; use LocalFS or any object "
            "implementing its interface (is_exist/upload/download/...)")

"""paddle.distributed.fleet (reference: fleet/base/fleet_base.py — the Fleet
singleton: init:170, distributed_optimizer:839, minimize:1367,
distributed_model:896).
"""
from __future__ import annotations

import os
from typing import Optional

from .. import mesh as mesh_mod
from ..env import get_rank, get_world_size, init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from . import meta_parallel  # noqa: F401
from .meta_parallel.parallel_layers import random as parallel_random  # noqa: F401
from .dataset import DatasetBase, InMemoryDataset, QueueDataset  # noqa: F401

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
    "is_collective": True,
    "role_maker": None,
}


class _UtilBase:
    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """fleet.util.all_reduce (fleet_base.py UtilBase): host-side numpy
        all-reduce across the training world — the gloo path in the
        reference. Values are REPLICATED host scalars/arrays (metrics,
        counters), so the reduction runs over the process dimension via
        process_allgather, not over the device mesh. Identity in a
        single-process world (the correct reduction over one rank)."""
        import numpy as np

        from ..env import get_world_size

        red = {"sum": np.sum, "min": np.min, "max": np.max}.get(mode)
        if red is None:
            raise ValueError(f"unsupported all_reduce mode {mode!r}; "
                             f"one of sum/min/max")
        arr = np.asarray(input)
        if get_world_size() <= 1:
            return arr
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(arr))
        return red(gathered, axis=0)

    def barrier(self):
        from ..collective import barrier

        barrier()


util = _UtilBase()


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None):
    """fleet.init (fleet_base.py:170). Builds the hybrid mesh from
    strategy.hybrid_configs over the local devices (single-process SPMD) —
    the reference's NCCL subgroup construction becomes mesh construction."""
    import jax

    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    ndev = len(jax.devices())
    mp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sh = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    dp = int(hc.get("dp_degree", -1))
    if dp == -1:
        denom = mp * pp * sh * sep
        if ndev % denom != 0:
            raise ValueError(
                f"{ndev} devices not divisible by mp*pp*sharding*sep={denom}"
            )
        dp = ndev // denom
    mesh_mod.set_mesh(mesh_mod.build_mesh({
        "data": dp, "pipe": pp, "sharding": sh, "sep": sep, "model": mp,
    }))
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"],
                               [dp, pp, sh, sep, mp])
    hcg = HybridCommunicateGroup(topo)
    if role_maker is None:
        from .base.role_maker import PaddleCloudRoleMaker

        role_maker = PaddleCloudRoleMaker(is_collective=is_collective)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg,
                        is_collective=is_collective, role_maker=role_maker)
    if getattr(strategy, "telemetry", False):
        _apply_telemetry_strategy(strategy.telemetry_configs)
    return fleet


def _apply_telemetry_strategy(cfg: dict):
    """strategy.telemetry knobs (ISSUE 6): resize the flight-recorder ring
    and bring up the per-rank exposition endpoint. Port 0 defers to
    FLAGS_telemetry_http_port (start_exposition's default resolution)."""
    from ...observability import configure_flight_recorder, start_exposition
    from ...observability.flight_recorder import get_flight_recorder

    cap = int(cfg.get("flight_recorder_capacity", 0) or 0)
    if cap and cap != get_flight_recorder().capacity:
        configure_flight_recorder(capacity=cap)
    port = int(cfg.get("http_port", 0) or 0)
    start_exposition(port=port if port else None)


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def _get_strategy() -> DistributedStrategy:
    return _fleet_state["strategy"] or DistributedStrategy()


def distributed_model(model):
    """fleet.distributed_model (fleet_base.py:896): wrap per parallel mode.
    On TPU the wrappers are thin — sharding comes from parameter specs; they
    exist for API parity and to place parameters onto the mesh."""
    from .meta_parallel import (
        PipelineParallel, ShardingParallel, TensorParallel,
    )
    from ..parallel import DataParallel

    hcg = _fleet_state["hcg"]
    if hcg is None:
        init()
        hcg = _fleet_state["hcg"]
    _place_params_on_mesh(model)
    mode = hcg.get_parallel_mode()
    strategy = _get_strategy()
    if mode == "pipeline":
        return PipelineParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy)
    if mode == "tensor_parallel":
        return TensorParallel(model, hcg, strategy)
    return DataParallel(
        model,
        find_unused_parameters=bool(
            getattr(strategy, "find_unused_parameters", False)))


def _place_params_on_mesh(model):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh_mod.get_mesh()
    if m is None or m.size == 1:
        return
    for p in model.parameters():
        spec = getattr(p, "dist_spec", None) or P()
        # model code annotates the FULL hybrid spec unconditionally; axes
        # absent from this mesh must drop out, not crash
        spec = mesh_mod.sanitize_spec(spec, m)
        p._value = jax.device_put(p._value, NamedSharding(m, spec))


def distributed_optimizer(optimizer, strategy=None):
    """fleet.distributed_optimizer (fleet_base.py:839) →
    HybridParallelOptimizer (hybrid_parallel_optimizer.py:170).

    Strategy toggles that rewrote programs in the reference
    (sharding_optimizer.py, gradient_merge_optimizer.py, localsgd_optimizer)
    become markers the compiled step reads."""
    st = strategy or _get_strategy()
    if getattr(st, "dgc", False):
        # DGC meta-optimizer (reference meta_optimizers/dgc_optimizer.py):
        # applies only to Momentum, swapping in the DGC update rule
        from ...optimizer import DGCMomentum, Momentum

        if type(optimizer) is Momentum:
            # dgc_configs is always fully populated (strategy defaults merge)
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                use_nesterov=optimizer._nesterov,
                weight_decay=optimizer._weight_decay,
                grad_clip=optimizer._grad_clip,
                **st.dgc_configs,
            )
    if getattr(st, "lars", False):
        # reference meta_optimizers/lars_optimizer.py: _can_apply on
        # Momentum; swap in the layer-wise-adaptive update
        from ...optimizer import Lars, Momentum

        if type(optimizer) is Momentum:
            optimizer = Lars(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                **st.lars_configs,
            )
    if getattr(st, "lamb", False):
        # reference meta_optimizers/lamb_optimizer.py: _can_apply on Adam
        from ...optimizer import Adam, Lamb

        if type(optimizer) is Adam:
            lamb_kw = dict(st.lamb_configs)
            excl = lamb_kw.pop("exclude_from_weight_decay", [])
            optimizer = Lamb(
                learning_rate=optimizer._learning_rate,
                beta1=optimizer._beta1,
                beta2=optimizer._beta2,
                epsilon=optimizer._epsilon,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                exclude_from_weight_decay_fn=(
                    (lambda p: any(n in p.name for n in excl))
                    if excl else None),
                **lamb_kw,
            )
    if getattr(st, "gradient_merge", False):
        # reference meta_optimizers/gradient_merge_optimizer.py
        from .meta_optimizers import GradientMergeOptimizer

        optimizer = GradientMergeOptimizer(optimizer,
                                           **st.gradient_merge_configs)
    if getattr(st, "localsgd", False):
        # reference meta_optimizers/localsgd_optimizer.py (k-step local
        # updates, then parameter averaging over the data axis)
        from .meta_optimizers import LocalSGDOptimizer

        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=st.localsgd_configs.get("k_steps", 1),
            begin_step=st.localsgd_configs.get("begin_step", 1))
    if getattr(st, "sharding", False) or int(
            st.hybrid_configs.get("sharding_degree", 1)) > 1:
        # ZeRO stage 1/2: shard optimizer slots over the 'sharding' axis
        optimizer._slot_shard_axis = "sharding"
    from .meta_parallel.hybrid_parallel_optimizer import HybridParallelOptimizer

    return HybridParallelOptimizer(optimizer, _fleet_state["hcg"], st)


# ----------------------------------------------------------- worker queries
def _ps_role_maker():
    """Role maker, for PS-mode queries only. Collective jobs keep sourcing
    rank/world from get_rank()/get_world_size() (RANK/WORLD_SIZE fallback +
    jax.process_index()), which the env-snapshot role maker cannot see."""
    if _fleet_state["is_collective"]:
        return None
    return _fleet_state["role_maker"]


def is_first_worker():
    rm = _ps_role_maker()
    return rm.is_first_worker() if rm is not None else get_rank() == 0

def worker_index():
    rm = _ps_role_maker()
    return rm.worker_index() if rm is not None else get_rank()

def worker_num():
    rm = _ps_role_maker()
    return rm.worker_num() if rm is not None else get_world_size()

def is_worker():
    rm = _ps_role_maker()
    return rm.is_worker() if rm is not None else True

def worker_endpoints(to_string=False):
    rm = _ps_role_maker()
    eps = (rm.get_trainer_endpoints() if rm is not None else None) or \
        os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170").split(",")
    return ",".join(eps) if to_string else eps

def server_num():
    rm = _ps_role_maker()
    return rm.server_num() if rm is not None else 0

def server_index():
    rm = _ps_role_maker()
    return rm.server_index() if rm is not None else 0

def server_endpoints(to_string=False):
    rm = _ps_role_maker()
    eps = rm.get_pserver_endpoints() if rm is not None else []
    return ",".join(eps) if to_string else eps

def is_server():
    rm = _ps_role_maker()
    return rm.is_server() if rm is not None else False

def is_heter_worker():
    """Heterogeneous-PS device worker? (reference: role_maker
    _is_heter_worker; TRAINING_ROLE=HETER_TRAINER)."""
    rm = _ps_role_maker()
    return rm.is_heter_worker() if rm is not None else False

def heter_worker_num():
    rm = _ps_role_maker()
    return rm.heter_worker_num() if rm is not None else 0

def barrier_worker():
    from ..collective import barrier

    barrier()


def init_worker(server_endpoints=None):
    """Connect this trainer to the PS servers (fleet_base.py:606 →
    TheOnePSRuntime). The fleet strategy picks the communicator mode:
    a_sync → AsyncCommunicator, a_sync_configs.k_steps>0 → GeoCommunicator
    (communicator.h:402/:566)."""
    from ..ps import TheOnePSRuntime

    return TheOnePSRuntime.current().init_worker(
        server_endpoints, strategy=_fleet_state["strategy"])

def init_server(*args, **kwargs):
    from ..ps import TheOnePSRuntime

    return TheOnePSRuntime.current().init_server(*args, **kwargs)

def run_server():
    from ..ps import TheOnePSRuntime

    return TheOnePSRuntime.current().run_server()

def stop_worker():
    from ..ps import TheOnePSRuntime

    TheOnePSRuntime.current().stop_worker()

def init_heter_worker(background=True):
    """Bind this heter worker's advertised endpoint (reference: the heter
    worker starts its heter_server inside the training process —
    heter_server.cc; launch only allocates and publishes the port). The
    service is a PsServer, so CPU trainers reach the device worker's dense
    tables over the same wire protocol.

    Returns the started server; with background=True the call returns
    immediately and training code may run alongside.
    """
    from ..ps import PsServer

    port = int(os.environ["PADDLE_PORT"])
    # listen on all interfaces: the launcher advertises this endpoint under
    # the --ips host, which need not be loopback
    srv = PsServer(host="0.0.0.0", port=port)
    srv.start(background=background)
    _fleet_state["heter_server"] = srv
    return srv


def save_persistables(executor=None, dirname=None, main_program=None, mode=0):
    pass


# make `fleet` importable as an object with these functions as attributes
import sys as _sys

fleet = _sys.modules[__name__]

__all__ = [
    "DistributedStrategy", "HybridCommunicateGroup", "CommunicateTopology",
    "init", "distributed_model", "distributed_optimizer", "get_hybrid_communicate_group",
    "is_first_worker", "worker_index", "worker_num", "util",
]

from .base.role_maker import (  # noqa: F401,E402
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
)

from .data_generator import (  # noqa: F401,E402
    DataGenerator,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)

UtilBase = _UtilBase


class Fleet:
    """Class form of the fleet singleton (reference fleet_base.py Fleet).
    The module-level functions ARE the implementation; instances delegate,
    so `Fleet().init(...)` and `fleet.init(...)` are the same object
    graph."""

    def __getattr__(self, item):
        return getattr(fleet, item)


__all__ += [
    "Fleet", "UtilBase", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
    "Role", "DataGenerator", "MultiSlotDataGenerator",
    "MultiSlotStringDataGenerator",
]

"""Fleet data generators (reference:
fleet/data_generator/data_generator.py): user subclasses override
generate_sample(line); run_from_stdin/run_from_memory emit the slot-text
format the DataFeed/InMemoryDataset ingestion understands:

    ids_num id1 id2 ... ids_num id1 ...   (one line per sample)
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    # -- user overrides ------------------------------------------------------
    def generate_sample(self, line):
        """Return a zero-arg iterator yielding [(slot_name, [feasign...])]"""
        raise NotImplementedError(
            "generate_sample must be overridden (return a local_iter "
            "yielding [(name, [feasign, ...]), ...])")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    # -- drivers -------------------------------------------------------------
    def run_from_stdin(self):
        self._run_lines(sys.stdin, sys.stdout)

    def run_from_memory(self, lines=None, out=None):
        """Offline variant: iterate `lines`, return the emitted strings
        (or write to `out`)."""
        emitted = []

        class _Sink:
            def write(self, s):
                emitted.append(s)

        self._run_lines(lines or [], out or _Sink())
        return "".join(emitted)

    def _run_lines(self, lines, out):
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            for parsed in it():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    for sample in self.generate_batch(batch)():
                        out.write(self._gen_str(sample))
                    batch = []
        if batch:
            for sample in self.generate_batch(batch)():
                out.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Numeric feasigns; tracks per-slot dtype in proto_info
    (reference MultiSlotDataGenerator)."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        if self._proto_info is None:
            self._proto_info = []
            for name, feas in line:
                dtype = "float" if any(isinstance(f, float) for f in feas) \
                    else "uint64"
                self._proto_info.append((name, dtype))
        if len(line) != len(self._proto_info):
            raise ValueError(
                f"sample has {len(line)} slots; the first sample "
                f"established {len(self._proto_info)} — slot sets must "
                "stay fixed (reference contract)")
        parts = []
        for (name, feas), (pname, _) in zip(line, self._proto_info):
            if name != pname:
                raise ValueError(
                    f"slot order changed: expected {pname}, got {name}")
            parts.append(str(len(feas)))
            parts.extend(str(f) for f in feas)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String feasigns, emitted verbatim (reference
    MultiSlotStringDataGenerator)."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        parts = []
        for name, feas in line:
            parts.append(str(len(feas)))
            parts.extend(str(f) for f in feas)
        return " ".join(parts) + "\n"

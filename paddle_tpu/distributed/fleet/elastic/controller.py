"""Goodput-maximizing elastic controller for the unified train+serve fleet.

PR 10 made any world size resumable and PR 14 made replica eviction
lossless, but until now every scale change in the repo was a *failure
response*: the reshard path ran after a crash, the drain path ran after a
watchdog eviction. This module closes ROADMAP item 2 by adding the
missing decision layer — a policy loop that watches the signals the repo
already emits and moves capacity *ahead* of failures:

  signal                          source
  ------------------------------  --------------------------------------
  preemption notice               robustness.preemption.PreemptionHandler
                                  (flag-file poll / SIGTERM latch)
  step-time p99 / straggler skew  observability step_time_skew gauge +
                                  aggregated step-time percentiles
  serve queue depth / tail ms     serving.scheduler serve_queue_depth
                                  gauge + replica latency percentiles
  spare capacity                  ElasticManager membership (TTL leases)

  decision                        actuation
  ------------------------------  --------------------------------------
  preempt_shrink                  timed emergency save + PR-10 reshard
                                  BEFORE the SIGTERM grace expires
  grow_train                      ElasticManager.wait_for_np + reshard up
  serve_up / serve_down           ReplicaSet.scale_up / scale_down
                                  (the PR-14 drain + re-admit path —
                                  zero dropped requests)
  train_to_serve / serve_to_train chip arbitration for diurnal traffic
  shed_straggler                  reshard the slow host out of the ring

Determinism contract: :meth:`ScalePolicy.decide` is a PURE function of a
:class:`FleetSignals` snapshot. All state a decision depends on —
including the hysteresis clock of the last scale action — rides IN the
snapshot, so a recorded signal sequence replays to the identical decision
sequence (tests/test_fleet_controller.py pins this). Every non-noop
decision is logged through the observability event plane and counted on
``fleet_decisions_total{action=}``.

The optimization target is goodput — useful tokens/s × availability —
accounted by :class:`GoodputLedger`: every chip-second of the fleet is
attributed to exactly one account (useful train tokens, useful serve
tokens, save/reshard/compile/drain overhead, recompute, or idle), so the
policy's value over the reactive baseline is a single gated number
(tools/chaos_train.py fleet phase, tools/bench_gate.py --fleet-artifact).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

__all__ = [
    "ACTIONS", "FleetSignals", "Decision", "ScalePolicy", "ReactivePolicy",
    "GoodputLedger", "FleetController", "LEDGER_ACCOUNTS",
]

# every action the policy may emit; "none" is the explicit no-op so the
# decision log is a total function of the tick sequence
ACTIONS = (
    "none",            # nothing to do (or hysteresis cooldown)
    "preempt_shrink",  # preemption notice: save + reshard before grace ends
    "shed_straggler",  # reshard a slow host out of the training ring
    "grow_train",      # spare capacity observed: reshard the world up
    "serve_up",        # serving overloaded, free chip available
    "serve_down",      # serving idle, no train demand for the chip
    "train_to_serve",  # serving overloaded, no free chip: take one from train
    "serve_to_train",  # serving idle: hand the chip to training
)


def _get_event_log():
    from ....observability.events import get_event_log

    return get_event_log()


def _m_decisions():
    from ....observability.metrics import get_registry

    return get_registry().counter(
        "fleet_decisions_total",
        help="elastic controller decisions actuated", labels=("action",))


@dataclass(frozen=True)
class FleetSignals:
    """One immutable snapshot of everything a decision may depend on.

    Frozen on purpose: ``ScalePolicy.decide`` takes nothing else, so
    pickling the snapshot sequence of a run is a complete replay script.
    ``last_scale_clock`` is the hysteresis state — it lives in the
    snapshot (stamped by whoever assembles it), NOT in the policy, so the
    policy object itself stays stateless.
    """

    clock: float                     # trace/virtual seconds, NOT wall time
    train_world: int
    serve_replicas: int
    total_chips: int
    free_chips: int = 0              # healthy chips assigned to neither side
    spare_hosts: int = 0             # registered members beyond the world
    step_time_p99_ms: float = 0.0
    step_time_skew: float = 0.0      # straggler gauge: (max-min)/mean step ms
    serve_queue_depth: int = 0
    serve_latency_p99_ms: float = 0.0
    preempt_notice: bool = False     # PreemptionHandler.requested (flag poll)
    preempt_grace_s: float = 0.0
    last_scale_clock: float = float("-inf")
    # telemetry-derived signals (ISSUE 18 SignalsAdapter). Defaulted so
    # snapshots recorded before the adapter existed still construct and
    # replay to the same decisions; a plant that doesn't expose them just
    # leaves the defaults.
    serve_ttft_p99_ms: float = 0.0   # windowed time-to-first-token tail
    slo_fast_burn: float = 0.0       # error-budget burn, fast window
    slo_slow_burn: float = 0.0       # error-budget burn, slow window
    heartbeat_age_max_s: float = 0.0  # oldest replica watchdog heartbeat
    # zero-cold-start plane (ISSUE 19). Defaulted for the same replay
    # reason: PR-17/18 snapshot sequences construct unchanged and decide
    # identically (nothing in ScalePolicy.decide reads these — they are
    # observability fields the decision records carry, stamped from the
    # ReplicaSet boot ledger via the warm_boot_counts duck-hook).
    warm_boots: int = 0              # cumulative warm boots completed ok
    warm_boot_timeouts: int = 0      # boots that fell back to cold


@dataclass(frozen=True)
class Decision:
    """One policy verdict for one tick."""

    action: str
    reason: str
    clock: float
    amount: int = 1

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"action must be one of {ACTIONS}, got {self.action!r}")


class ScalePolicy:
    """Deterministic goodput-maximizing scale policy.

    Priority order (first match wins):

    1. preemption notice  — the grace clock is already running; nothing
       outranks getting the emergency save + reshard done before it
       expires. Exempt from the cooldown for the same reason.
    2. straggler skew     — a slow host taxes every step of the whole
       ring; world−1 at full speed beats world at the straggler's pace.
    3. serve overload     — queue depth or tail latency over threshold:
       add a replica from the free pool, else take a chip from training
       (day traffic pays for itself in the availability term of goodput).
    4. serve idle         — replicas above the floor with an empty queue:
       hand chips back to training (night).
    5. spare capacity     — registered members beyond the world: grow.

    Rules 2-5 respect a cooldown of ``cooldown_s`` since
    ``signals.last_scale_clock`` so one burst of signal noise cannot
    thrash reshard/drain machinery whose cost the ledger charges.
    """

    def __init__(self, min_train_world: int = 1,
                 max_train_world: Optional[int] = None,
                 min_serve_replicas: int = 1,
                 max_serve_replicas: Optional[int] = None,
                 queue_high: int = 6, queue_low: int = 0,
                 serve_p99_high_ms: float = 2500.0,
                 skew_high: float = 0.5,
                 cooldown_s: float = 2.0,
                 slo_burn_high: Optional[float] = None,
                 warm_boot: bool = False):
        self.min_train_world = int(min_train_world)
        self.max_train_world = max_train_world
        self.min_serve_replicas = int(min_serve_replicas)
        self.max_serve_replicas = max_serve_replicas
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.serve_p99_high_ms = float(serve_p99_high_ms)
        self.skew_high = float(skew_high)
        self.cooldown_s = float(cooldown_s)
        # SLO burn-rate trigger (ISSUE 18): OFF by default (None) so
        # decision sequences recorded before the burn signal existed
        # replay bit-identically; set (e.g. 1.0) to treat a slow-window
        # budget burn as serve overload alongside depth/latency.
        self.slo_burn_high = (None if slo_burn_high is None
                              else float(slo_burn_high))
        # zero-cold-start actuation (ISSUE 19): OFF by default so every
        # recorded decision sequence replays bit-identically (the knob
        # changes HOW serve_up/train_to_serve are actuated — warm standby
        # with readiness probe + boot budget — never WHAT is decided;
        # decide() does not read it).
        self.warm_boot = bool(warm_boot)

    # ------------------------------------------------------------ decide
    def decide(self, s: FleetSignals) -> Decision:
        """Pure: (signals) -> Decision. No reads of self beyond the
        constructor thresholds, no clocks, no RNG, no mutation."""
        train_can_shrink = s.train_world > self.min_train_world
        train_can_grow = (self.max_train_world is None
                          or s.train_world < self.max_train_world)
        serve_can_grow = (self.max_serve_replicas is None
                          or s.serve_replicas < self.max_serve_replicas)
        serve_can_shrink = s.serve_replicas > self.min_serve_replicas

        if s.preempt_notice and train_can_shrink:
            return Decision(
                "preempt_shrink", "preemption notice: emergency save + "
                "reshard inside the grace window", s.clock)

        if (s.clock - s.last_scale_clock) < self.cooldown_s:
            return Decision("none", "cooldown", s.clock)

        if s.step_time_skew >= self.skew_high and train_can_shrink:
            return Decision(
                "shed_straggler", "straggler skew over threshold: the ring "
                "is worth more without the slow host", s.clock)

        overloaded = (s.serve_queue_depth >= self.queue_high
                      or s.serve_latency_p99_ms >= self.serve_p99_high_ms
                      or (self.slo_burn_high is not None
                          and s.slo_slow_burn >= self.slo_burn_high))
        if overloaded and serve_can_grow:
            if s.free_chips > 0:
                return Decision(
                    "serve_up", "serving overloaded, free chip available",
                    s.clock)
            if train_can_shrink:
                return Decision(
                    "train_to_serve", "serving overloaded, no free chip: "
                    "arbitrating one away from training", s.clock)

        serve_idle = (s.serve_queue_depth <= self.queue_low
                      and s.serve_latency_p99_ms
                      < 0.5 * self.serve_p99_high_ms)
        if serve_idle and serve_can_shrink:
            if train_can_grow:
                return Decision(
                    "serve_to_train", "serving idle: handing the chip to "
                    "training", s.clock)
            return Decision(
                "serve_down", "serving idle above the replica floor",
                s.clock)

        if (s.free_chips > 0 or s.spare_hosts > 0) and train_can_grow \
                and not overloaded:
            return Decision(
                "grow_train", "spare capacity observed: growing the world",
                s.clock)

        return Decision("none", "steady state", s.clock)


class ReactivePolicy(ScalePolicy):
    """The pre-PR-17 baseline: never decides anything. Scale changes
    happen only as failure responses outside the policy (a crash after
    the grace window expires, a watchdog eviction) — exactly the repo's
    behavior before this controller existed. The fleet chaos phase runs
    the same trace under both policies; the goodput ratio between them is
    the controller's gated value."""

    def decide(self, s: FleetSignals) -> Decision:
        return Decision("none", "reactive baseline: failures only", s.clock)


# ---------------------------------------------------------------- ledger
LEDGER_ACCOUNTS = (
    "train_useful",  # chip-seconds advancing never-seen optimizer steps
    "serve_useful",  # chip-seconds a replica spent admitting/decoding
    "save",          # checkpoint commits (emergency or resize)
    "reshard",       # PR-10 shard-geometry transforms + rebuilds
    "compile",       # warm-up of a resized ring / freshly booted replica
    "drain",         # replica drain + preempted chip wind-down
    "recompute",     # replaying steps lost to a crash (reactive baseline)
    "idle",          # healthy chip, no work assigned
)


class GoodputLedger:
    """Chip-second accounting: every chip-second of the fleet horizon is
    attributed to exactly one of :data:`LEDGER_ACCOUNTS`.

    Goodput is the metric fleets buy — useful tokens per second times
    availability::

        goodput = (train_tokens + serve_tokens) / horizon_s * availability

    where availability is the serve completion fraction (completed /
    submitted) over the horizon. ``verify_conservation`` checks that the
    accounts sum to the chip-seconds that actually existed — an
    attribution that silently drops time would flatter any policy.
    """

    def __init__(self):
        self.accounts: Dict[str, float] = {a: 0.0 for a in LEDGER_ACCOUNTS}
        self.train_tokens = 0
        self.serve_tokens = 0
        self.serve_submitted = 0
        self.serve_completed = 0

    def charge(self, account: str, chips: float, seconds: float = 1.0):
        if account not in self.accounts:
            raise ValueError(
                f"account must be one of {LEDGER_ACCOUNTS}, got {account!r}")
        self.accounts[account] += float(chips) * float(seconds)

    def tokens(self, kind: str, n: int):
        if kind == "train":
            self.train_tokens += int(n)
        elif kind == "serve":
            self.serve_tokens += int(n)
        else:
            raise ValueError(f"kind must be train|serve, got {kind!r}")

    @property
    def chip_seconds(self) -> float:
        return sum(self.accounts.values())

    @property
    def availability(self) -> float:
        if self.serve_submitted == 0:
            return 1.0
        return self.serve_completed / self.serve_submitted

    def goodput(self, horizon_s: float) -> float:
        toks = self.train_tokens + self.serve_tokens
        return (toks / float(horizon_s)) * self.availability

    def verify_conservation(self, expected_chip_seconds: float,
                            tol: float = 1e-6) -> bool:
        return abs(self.chip_seconds - expected_chip_seconds) <= tol

    def summary(self) -> dict:
        total = self.chip_seconds or 1.0
        return {
            "accounts": {k: round(v, 3) for k, v in self.accounts.items()},
            "chip_seconds": round(self.chip_seconds, 3),
            "useful_fraction": round(
                (self.accounts["train_useful"]
                 + self.accounts["serve_useful"]) / total, 4),
            "train_tokens": self.train_tokens,
            "serve_tokens": self.serve_tokens,
            "serve_submitted": self.serve_submitted,
            "serve_completed": self.serve_completed,
            "availability": round(self.availability, 4),
        }


# ------------------------------------------------------------ controller
class FleetController:
    """Signal → decision → actuation loop over duck-typed plants.

    ``train`` must expose: ``world`` (int), ``step_time_p99_ms()``,
    ``step_time_skew()``, ``preempt_pending()`` (the flag-file poll),
    ``preempt_grace_s()``, and the actuators ``preempt_shrink()``,
    ``shed_straggler()``, ``grow()``, ``release_chip()``.

    ``serve`` must expose: ``replicas`` (int), ``queue_depth`` (int),
    ``latency_p99_ms()``, and the actuators ``scale_up()``,
    ``scale_down()`` (the PR-14 drain + re-admit path).

    The controller owns chip inventory (``total_chips`` −
    ``quarantined`` − assigned = free) and the hysteresis clock; the
    policy owns nothing. ``tick(clock)`` assembles the snapshot, asks the
    policy, actuates, and appends ``(signals, decision)`` to
    ``self.records`` — the replay log the determinism test re-decides
    from.
    """

    def __init__(self, policy: ScalePolicy, train, serve,
                 total_chips: int, ledger: Optional[GoodputLedger] = None):
        self.policy = policy
        self.train = train
        self.serve = serve
        self.total_chips = int(total_chips)
        self.quarantined = 0
        self.ledger = ledger or GoodputLedger()
        self.records: List[tuple] = []   # (FleetSignals, Decision)
        self.decisions: List[Decision] = []  # non-noop only
        # actuation OUTCOMES (ISSUE 19): what happened when a decision
        # ran — e.g. a warm serve_up that overran its boot budget records
        # outcome="warm_boot_timeout" here. Kept OUT of self.records so
        # replay stays a pure function of (signals, decision).
        self.actuations: List[dict] = []
        self._last_scale_clock = float("-inf")

    # ------------------------------------------------------------ signals
    @property
    def free_chips(self) -> int:
        return max(0, self.total_chips - self.quarantined
                   - self.train.world - self.serve.replicas)

    def signals(self, clock: float) -> FleetSignals:
        # a telemetry-backed serve plant (signals.SignalsAdapter) advances
        # its histogram windows on the decision clock; plants without the
        # hook (and without the optional signal methods below) are served
        # by the FleetSignals defaults
        observe = getattr(self.serve, "observe", None)
        if observe is not None:
            observe(float(clock))
        zero = lambda: 0.0  # noqa: E731 - duck default
        burn = getattr(self.serve, "slo_burn", None)
        fast_burn, slow_burn = burn() if burn is not None else (0.0, 0.0)
        counts = getattr(self.serve, "warm_boot_counts", None)
        boot_counts = counts() if counts is not None else {}
        return FleetSignals(
            clock=float(clock),
            train_world=int(self.train.world),
            serve_replicas=int(self.serve.replicas),
            total_chips=self.total_chips,
            free_chips=self.free_chips,
            spare_hosts=int(getattr(self.train, "spare_hosts", lambda: 0)()),
            step_time_p99_ms=float(self.train.step_time_p99_ms()),
            step_time_skew=float(self.train.step_time_skew()),
            serve_queue_depth=int(self.serve.queue_depth),
            serve_latency_p99_ms=float(self.serve.latency_p99_ms()),
            preempt_notice=bool(self.train.preempt_pending()),
            preempt_grace_s=float(self.train.preempt_grace_s()),
            last_scale_clock=self._last_scale_clock,
            serve_ttft_p99_ms=float(
                getattr(self.serve, "ttft_p99_ms", zero)()),
            slo_fast_burn=float(fast_burn),
            slo_slow_burn=float(slow_burn),
            heartbeat_age_max_s=float(
                getattr(self.serve, "heartbeat_age_max_s", zero)()),
            warm_boots=int(boot_counts.get("warm_boots", 0)),
            warm_boot_timeouts=int(
                boot_counts.get("warm_boot_timeouts", 0)),
        )

    # --------------------------------------------------------------- tick
    def tick(self, clock: float) -> Decision:
        s = self.signals(clock)
        d = self.policy.decide(s)
        self.records.append((s, d))
        if d.action != "none":
            self._actuate(d)
        return d

    def replay(self) -> bool:
        """Re-decide every recorded snapshot; True iff the decision
        sequence is bit-identical (the determinism contract)."""
        return all(self.policy.decide(s) == d for s, d in self.records)

    # ------------------------------------------------------------ actuate
    def _serve_scale_up(self):
        """serve_up/train_to_serve actuation. With the policy's
        ``warm_boot`` knob on, the replica boots as a warm standby
        (pre-compiled, readiness-probed, budget-bounded — ISSUE 19);
        plants without the ``warm=`` kwarg or a boot ledger fall back to
        the plain cold scale_up. Returns the boot outcome string."""
        if getattr(self.policy, "warm_boot", False):
            try:
                self.serve.scale_up(warm=True)
            except TypeError:  # plant predates the warm kwarg
                self.serve.scale_up()
                return "ok"
            boot = getattr(self.serve, "last_boot", None)
            if boot and boot.get("mode") == "cold":
                # warm path fell back: the PREVIOUS record is the timeout
                return "warm_boot_timeout"
            return "ok"
        self.serve.scale_up()
        return "ok"

    def _actuate(self, d: Decision):
        outcome = "ok"
        if d.action == "preempt_shrink":
            self.train.preempt_shrink()
        elif d.action == "shed_straggler":
            self.quarantined += 1   # the slow host is not free capacity
            self.train.shed_straggler()
        elif d.action == "grow_train":
            self.train.grow()
        elif d.action == "serve_up":
            outcome = self._serve_scale_up()
        elif d.action == "serve_down":
            self.serve.scale_down()
        elif d.action == "train_to_serve":
            self.train.release_chip()
            outcome = self._serve_scale_up()
        elif d.action == "serve_to_train":
            self.serve.scale_down()
            self.train.grow()
        else:  # pragma: no cover - Decision.__post_init__ guards this
            raise ValueError(f"unknown action {d.action!r}")
        self._last_scale_clock = d.clock
        self.decisions.append(d)
        self.actuations.append(
            {"action": d.action, "clock": d.clock, "outcome": outcome})
        _m_decisions().labels(action=d.action).inc()
        _get_event_log().info(
            "fleet", f"decision actuated: {d.action}", action=d.action,
            reason=d.reason, outcome=outcome, clock=round(d.clock, 3),
            train_world=int(self.train.world),
            serve_replicas=int(self.serve.replicas),
            free_chips=self.free_chips)

    # ----------------------------------------------------------- exposure
    def decision_log(self) -> List[dict]:
        return [{"action": d.action, "clock": d.clock, "reason": d.reason}
                for d in self.decisions]

"""Telemetry-derived fleet signals: close the observe→decide loop.

PR 17's :class:`FleetController` decides from a :class:`FleetSignals`
snapshot, but the chaos harness assembled that snapshot from *plant
probes* — synthetic queue ages standing in for latency, hand-fed skew.
The serving runtime meanwhile emits the real thing (PR 14 + ISSUE 18):
``serve_queue_depth`` gauge, ``serve_request_latency_ms`` /
``serve_ttft_ms`` histograms with trace exemplars, per-replica batch
occupancy and KV-block gauges, ``step_time_skew`` from the aggregator,
and watchdog heartbeat ages. This module derives the decision inputs
from that live telemetry instead:

- :class:`HistogramWindow` — windowed quantiles over a CUMULATIVE
  metrics histogram. Prometheus histograms only ever grow, so a policy
  reading ``Histogram.quantile`` would decide on the job's life-to-date
  distribution and never notice load subsiding. The window samples the
  cumulative bucket counts on a clock and computes quantiles over the
  *delta* between now and the newest sample at least ``window_s`` old —
  the same ``rate()``-then-``histogram_quantile()`` shape a Prometheus
  alert uses.
- :class:`SloBurnRate` — multi-window error-budget burn (SRE-workbook
  style): of the observations in a window, what fraction missed the
  budget bound, divided by the SLO's allowed error fraction. Burn > 1 on
  the slow window means the budget is being spent faster than it
  regenerates; the fast window catches sudden breakage. Advisory by
  default: :class:`ScalePolicy` only consumes it when ``slo_burn_high``
  is set, so recorded decision sequences replay unchanged.
- :class:`SignalsAdapter` — a drop-in ``serve`` plant for
  :class:`FleetController` (same duck: ``replicas`` / ``queue_depth`` /
  ``latency_p99_ms()`` / ``scale_up()`` / ``scale_down()``) whose signal
  reads come from the live registry + ReplicaSet while actuation
  delegates to the wrapped plant. ``ScalePolicy.decide`` stays a pure
  function of the snapshot — the adapter only changes where the numbers
  in the snapshot come from.

tools/chaos_train.py ``run_fleet --signals adapter`` swaps the adapter
in over the recorded plant trace and asserts the decision sequence (or
goodput within band) against the probe-driven run.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, List, Optional, Tuple

__all__ = ["HistogramWindow", "SloBurnRate", "SignalsAdapter"]


def _get_registry():
    from ....observability.metrics import get_registry

    return get_registry()


def _find_histogram(registry, name: str):
    """The raw (unlabelled) Histogram child for ``name``, or None if the
    family doesn't exist yet — signal sources are looked up lazily so the
    adapter can be built before the serving modules register metrics."""
    fam = registry.get(name)
    if fam is None or fam.kind != "histogram" or fam.label_names:
        return None
    return fam.bind()


class HistogramWindow:
    """Windowed quantiles over a cumulative metrics.Histogram.

    ``sample(clock)`` snapshots the cumulative bucket counts;
    ``quantile(q, window_s)`` interpolates over the bucket-count *delta*
    between the newest snapshot and the newest one at least ``window_s``
    older (life-to-date when only one snapshot exists yet). Clocks are
    whatever the caller ticks — virtual trace seconds in the chaos
    harness, wall seconds live — as long as they are monotonic.
    """

    def __init__(self, hist_fn: Callable[[], Optional[object]],
                 horizon_s: float = 600.0):
        self._hist_fn = hist_fn
        self.horizon_s = float(horizon_s)
        # (clock, cumulative count, tuple(cumulative bucket counts))
        self._samples: deque = deque()

    def sample(self, clock: float) -> None:
        hist = self._hist_fn()
        if hist is None:
            return
        clock = float(clock)
        self._samples.append(
            (clock, hist.count, tuple(hist.bucket_counts)))
        while (len(self._samples) > 1
               and self._samples[0][0] < clock - self.horizon_s):
            self._samples.popleft()

    def _delta(self, window_s: float) -> Tuple[int, Optional[list]]:
        """(delta count, delta bucket counts) over the window ending at
        the newest sample."""
        if not self._samples:
            return 0, None
        c1, n1, b1 = self._samples[-1]
        base = None
        for c0, n0, b0 in reversed(self._samples):
            if c1 - c0 >= window_s:
                base = (n0, b0)
                break
        if base is None:
            if len(self._samples) > 1:
                base = (self._samples[0][1], self._samples[0][2])
            else:  # single sample: the interval is the histogram's life
                base = (0, (0,) * len(b1))
        n0, b0 = base
        return n1 - n0, [x - y for x, y in zip(b1, b0)]

    def quantile(self, q: float, window_s: float) -> float:
        """Interval q-quantile, Prometheus histogram_quantile style. An
        empty window reports 0.0 (no traffic is not slow traffic); a
        target landing in the +Inf bucket reports the last finite bound
        (no per-interval max exists to do better)."""
        hist = self._hist_fn()
        d_count, d_buckets = self._delta(window_s)
        if hist is None or not d_count:
            return 0.0
        bounds = hist.bounds
        target = q * d_count
        prev_c = 0
        prev_b = 0.0
        for b, c in zip(bounds, d_buckets):
            if c >= target and c > prev_c:
                return prev_b + (b - prev_b) * (target - prev_c) \
                    / (c - prev_c)
            prev_c, prev_b = c, b
        return bounds[-1] if bounds else 0.0

    def bad_fraction(self, budget: float, window_s: float) -> float:
        """Fraction of interval observations ABOVE ``budget``. Counted
        conservatively against the tightest bucket bound >= budget; when
        the budget exceeds every finite bound, anything in +Inf counts as
        bad (indistinguishable from a miss)."""
        hist = self._hist_fn()
        d_count, d_buckets = self._delta(window_s)
        if hist is None or not d_count:
            return 0.0
        good = 0
        for b, c in zip(hist.bounds, d_buckets):
            if b >= budget:
                good = c
                break
        else:
            good = d_buckets[-1] if d_buckets else 0
        return max(0.0, 1.0 - good / d_count)


class SloBurnRate:
    """Error-budget burn for one latency SLO over fast + slow windows.

    ``objective`` is the target good fraction (e.g. 0.9 = "90% of
    requests under ``budget_ms``"); the error budget is 1 − objective.
    ``burn()`` returns (fast, slow): each window's observed bad fraction
    divided by the error budget — 1.0 means the budget is consumed
    exactly as fast as it regenerates, higher means an active burn.
    """

    def __init__(self, window: HistogramWindow, budget_ms: float,
                 objective: float = 0.9, fast_window_s: float = 5.0,
                 slow_window_s: float = 30.0):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}")
        self.window = window
        self.budget_ms = float(budget_ms)
        self.error_budget = 1.0 - float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)

    def burn(self) -> Tuple[float, float]:
        fast = self.window.bad_fraction(self.budget_ms, self.fast_window_s)
        slow = self.window.bad_fraction(self.budget_ms, self.slow_window_s)
        return fast / self.error_budget, slow / self.error_budget


class SignalsAdapter:
    """FleetController serve plant whose signals come from live telemetry.

    Wraps an actuating plant (a chaos-harness ``_FleetServePlant``, a
    :class:`serving.ReplicaSet`, anything with ``scale_up``/``scale_down``
    and a ``replicas`` count) and answers the controller's signal reads
    from the metrics registry instead of plant probes:

      duck field / method        derived from
      -------------------------  -----------------------------------
      queue_depth                serve_queue_depth gauge
      latency_p99_ms()           serve_request_latency_ms windowed p99
      ttft_p99_ms()              serve_ttft_ms windowed p99
      slo_burn()                 max burn across both SLOs, per window
      heartbeat_age_max_s()      ReplicaSet.heartbeat_ages() max
      replicas                   wrapped plant (actuation truth)

    ``observe(clock)`` must tick once per controller tick (the
    controller's ``signals()`` calls it when present) so the windows
    advance on the same clock the policy decides on.
    """

    def __init__(self, plant, replica_set=None, registry=None,
                 window_s: float = 10.0,
                 latency_budget_ms: float = 2500.0,
                 ttft_budget_ms: float = 1000.0,
                 slo_objective: float = 0.9,
                 fast_window_s: float = 5.0,
                 slow_window_s: float = 30.0):
        self.plant = plant
        self.replica_set = replica_set if replica_set is not None \
            else getattr(plant, "replica_set", None)
        self._registry = registry if registry is not None \
            else _get_registry()
        self.window_s = float(window_s)
        horizon = max(4 * slow_window_s, 4 * window_s)
        self.latency_window = HistogramWindow(
            lambda: _find_histogram(self._registry,
                                    "serve_request_latency_ms"),
            horizon_s=horizon)
        self.ttft_window = HistogramWindow(
            lambda: _find_histogram(self._registry, "serve_ttft_ms"),
            horizon_s=horizon)
        self.latency_slo = SloBurnRate(
            self.latency_window, latency_budget_ms,
            objective=slo_objective, fast_window_s=fast_window_s,
            slow_window_s=slow_window_s)
        self.ttft_slo = SloBurnRate(
            self.ttft_window, ttft_budget_ms, objective=slo_objective,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s)

    # ------------------------------------------------------------ sampling
    def observe(self, clock: float) -> None:
        """Advance the histogram windows to ``clock`` (once per tick)."""
        self.latency_window.sample(clock)
        self.ttft_window.sample(clock)

    # ---------------------------------------------------- serve-plant duck
    @property
    def replicas(self) -> int:
        return int(self.plant.replicas)

    @property
    def queue_depth(self) -> int:
        fam = self._registry.get("serve_queue_depth")
        if fam is None or fam.label_names:
            return int(getattr(self.plant, "queue_depth", 0))
        return int(fam.value)

    def latency_p99_ms(self) -> float:
        return float(self.latency_window.quantile(0.99, self.window_s))

    def ttft_p99_ms(self) -> float:
        return float(self.ttft_window.quantile(0.99, self.window_s))

    def slo_burn(self) -> Tuple[float, float]:
        lf, ls = self.latency_slo.burn()
        tf, ts = self.ttft_slo.burn()
        return max(lf, tf), max(ls, ts)

    def heartbeat_age_max_s(self) -> float:
        rs = self.replica_set
        if rs is None:
            return 0.0
        ages: List[float] = rs.heartbeat_ages()
        return max(ages) if ages else 0.0

    def scale_up(self):
        return self.plant.scale_up()

    def scale_down(self):
        return self.plant.scale_down()

    # ---------------------------------------------------------- exposition
    def snapshot(self) -> dict:
        """Every derived signal at once (debug / artifact logging)."""
        fast, slow = self.slo_burn()
        out = {
            "queue_depth": self.queue_depth,
            "latency_p99_ms": round(self.latency_p99_ms(), 3),
            "ttft_p99_ms": round(self.ttft_p99_ms(), 3),
            "slo_fast_burn": round(fast, 4),
            "slo_slow_burn": round(slow, 4),
            "heartbeat_age_max_s": round(self.heartbeat_age_max_s(), 3),
        }
        for gname, key in (("serve_batch_occupancy", "batch_occupancy"),
                           ("serve_kv_blocks_in_use", "kv_blocks_in_use")):
            fam = self._registry.get(gname)
            if fam is None:
                continue
            vals = [child.value for _, child in fam.items()]
            if vals:
                out[key] = {"max": max(vals),
                            "mean": sum(vals) / len(vals)}
        return out

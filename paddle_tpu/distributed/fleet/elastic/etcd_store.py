"""Minimal etcd v3 client for elastic membership.

Reference: python/paddle/distributed/fleet/elastic/manager.py:245-282 —
the reference talks to etcd3 (lease grant/keepalive, put-with-lease,
prefix watch) through the python-etcd3 gRPC client. This client speaks
the SAME RPC surface over etcd's official v3 JSON/HTTP gateway
(grpc-gateway, served by default on the etcd client port since 3.2):
LeaseGrant, LeaseKeepAlive, Put, Range, DeleteRange, LeaseRevoke and the
streaming Watch — stdlib http.client only, since no gRPC runtime ships
in this environment. Keys/values cross the wire base64-encoded and
int64s as strings, per the gateway's JSON mapping.

Implements the store interface ElasticManager consumes (put/refresh/
get_prefix/delete) plus watch_prefix() for prompt scale detection.
"""
from __future__ import annotations

import base64
import http.client
import json
import threading
from typing import Callable, Optional

__all__ = ["Etcd3GatewayStore"]


def _b64(s) -> str:
    if isinstance(s, str):
        s = s.encode("utf-8")
    return base64.b64encode(s).decode("ascii")


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode("utf-8")


def _prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix query: range_end = prefix with its last byte + 1
    (trailing 0xff bytes drop, per the etcd client libraries)."""
    p = bytearray(prefix)
    while p:
        if p[-1] < 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return b"\x00"  # empty/overflow: whole keyspace


class Etcd3GatewayStore:
    def __init__(self, endpoint: str = "127.0.0.1:2379", timeout: float = 5.0):
        if "://" in endpoint:
            endpoint = endpoint.split("://", 1)[1]
        self.host, port = endpoint.rsplit(":", 1)
        self.port = int(port)
        self.timeout = timeout
        self._leases: dict = {}  # key -> lease id (int)
        self._lock = threading.Lock()

    # ---- one JSON rpc ------------------------------------------------------
    def _call(self, path: str, body: dict) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            payload = json.dumps(body)
            conn.request("POST", path, body=payload,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"etcd gateway {path} -> {resp.status}: {data[:200]!r}")
            out = json.loads(data) if data else {}
            # the gateway wraps streaming rpcs (keepalive) in {"result": ...}
            return out.get("result", out)
        finally:
            conn.close()

    # ---- lease lifecycle ---------------------------------------------------
    def _grant(self, ttl: int) -> int:
        out = self._call("/v3/lease/grant", {"TTL": str(int(ttl))})
        return int(out["ID"])

    def _keepalive(self, lease: int) -> bool:
        """True iff the lease is still live (gateway returns TTL 0/absent
        for an expired lease)."""
        try:
            out = self._call("/v3/lease/keepalive", {"ID": str(int(lease))})
        except RuntimeError:
            return False
        return int(out.get("TTL", 0) or 0) > 0

    # ---- ElasticManager store surface -------------------------------------
    def put(self, key: str, value: str, ttl: Optional[int] = None):
        lease = 0
        if ttl:
            with self._lock:
                cached = self._leases.get(key)
            if cached and self._keepalive(cached):
                lease = cached
            else:
                lease = self._grant(int(ttl))
                with self._lock:
                    self._leases[key] = lease
        body = {"key": _b64(key), "value": _b64(value)}
        if lease:
            body["lease"] = str(lease)
        self._call("/v3/kv/put", body)

    def refresh(self, key: str, ttl: int):
        with self._lock:
            lease = self._leases.get(key)
        if not (lease and self._keepalive(lease)):
            self.put(key, key.rsplit("/", 1)[-1], ttl=ttl)

    def get_prefix(self, prefix: str):
        pb = prefix.encode("utf-8")
        out = self._call("/v3/kv/range", {
            "key": _b64(pb), "range_end": _b64(_prefix_range_end(pb))})
        return [(_unb64(kv["key"]), _unb64(kv["value"]))
                for kv in out.get("kvs", [])]

    def delete(self, key: str):
        self._call("/v3/kv/deleterange", {"key": _b64(key)})
        with self._lock:
            lease = self._leases.pop(key, None)
        if lease:
            try:
                self._call("/v3/lease/revoke", {"ID": str(lease)})
            except RuntimeError:
                pass  # already expired

    # ---- prefix watch ------------------------------------------------------
    def watch_prefix(self, prefix: str,
                     handler: Callable[[str, str, Optional[str]], None],
                     stop_event: Optional[threading.Event] = None,
                     poll_timeout: float = 0.5):
        """Stream PUT/DELETE events for keys under `prefix` to
        handler(event_type, key, value) on a daemon thread; returns the
        (thread, stop_event) pair. The watch rides the gateway's
        chunked-streaming /v3/watch response.

        Shutdown contract: setting the stop event actually UNBLOCKS the
        pump and exits the thread — the socket read runs with a
        `poll_timeout` so the stop flag is re-checked at that cadence, and
        when the returned event is ours its set() also closes the
        HTTPConnection from the stopping thread, waking a blocked read
        immediately. (A plain `while not stop.is_set(): read()` never
        exits while the server is quiet: the read blocks forever and the
        thread + socket leak per watch.)"""
        own = stop_event is None
        stop = _WatchStop() if own else stop_event
        pb = prefix.encode("utf-8")

        def pump():
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=poll_timeout)
            if own:
                stop._conns.append(conn)
            try:
                req = json.dumps({"create_request": {
                    "key": _b64(pb),
                    "range_end": _b64(_prefix_range_end(pb))}})
                conn.request("POST", "/v3/watch", body=req,
                             headers={"Content-Type": "application/json"})
                resp = None
                while resp is None and not stop.is_set():
                    try:
                        resp = conn.getresponse()
                    except TimeoutError:
                        return  # server never answered the watch create
                buf = b""
                while not stop.is_set():
                    try:
                        chunk = resp.read1(65536)
                    except TimeoutError:
                        continue   # idle stream: re-check the stop flag
                    if not chunk:
                        return
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if not line.strip():
                            continue
                        msg = json.loads(line).get("result", {})
                        for ev in msg.get("events", []):
                            typ = ev.get("type", "PUT")
                            kv = ev.get("kv", {})
                            key = _unb64(kv.get("key", ""))
                            val = (_unb64(kv["value"])
                                   if kv.get("value") else None)
                            handler(typ, key, val)
            except (OSError, http.client.HTTPException):
                return  # connection torn down (stop or server gone)
            finally:
                conn.close()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        return t, stop


class _WatchStop(threading.Event):
    """Stop event whose set() also closes the watch connection, so a pump
    blocked in a socket read wakes immediately instead of at the next
    poll-timeout tick."""

    def __init__(self):
        super().__init__()
        self._conns: list = []

    def set(self):
        super().set()
        for conn in self._conns:
            try:
                conn.close()
            except (OSError, ValueError):
                # close on an already-dead connection is the expected race
                # here (the pump may have closed it first); anything else
                # should surface (rule C003)
                pass

"""Elastic training manager.

Reference: distributed/fleet/elastic/manager.py:130 — etcd-backed membership
(TTL-leased node registrations + heartbeat, manager.py:245–282), endpoint
rewrite on scale events, local relaunch. Here the store is pluggable: an
in-process dict store for tests/single-host, etcd when a client object is
injected (no etcd runtime ships in this environment).
"""
from __future__ import annotations

import threading
import time

__all__ = ["ElasticManager", "ElasticStatus", "LocalKVStore",
           "ElasticController", "Etcd3GatewayStore",
           "FleetController", "FleetSignals", "Decision", "ScalePolicy",
           "ReactivePolicy", "GoodputLedger",
           "SignalsAdapter", "HistogramWindow", "SloBurnRate"]

# controller.py exports, lazy for the same reason as the etcd store: this
# package must stay stdlib-light at import (launch-plane code paths)
_CONTROLLER_EXPORTS = frozenset({
    "FleetController", "FleetSignals", "Decision", "ScalePolicy",
    "ReactivePolicy", "GoodputLedger", "ACTIONS", "LEDGER_ACCOUNTS"})

# signals.py exports (ISSUE 18): telemetry-derived decision inputs
_SIGNALS_EXPORTS = frozenset({
    "SignalsAdapter", "HistogramWindow", "SloBurnRate"})


def __getattr__(name):
    if name == "Etcd3GatewayStore":  # lazy: stdlib-only, but keep import light
        from .etcd_store import Etcd3GatewayStore

        return Etcd3GatewayStore
    if name in _CONTROLLER_EXPORTS:
        from . import controller

        return getattr(controller, name)
    if name in _SIGNALS_EXPORTS:
        from . import signals

        return getattr(signals, name)
    raise AttributeError(name)


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class LocalKVStore:
    """In-process TTL key-value store with the tiny etcd surface the manager
    needs (put with lease / get_prefix / delete; refresh is kept for store
    adapters that lease-refresh, though the manager re-puts instead so an
    expired lease recovers). Injectable stand-in for an etcd3 client."""

    def __init__(self):
        self._data = {}  # key → (value, expire_ts or None)
        self._lock = threading.Lock()

    def put(self, key, value, ttl=None):
        with self._lock:
            self._data[key] = (value, time.time() + ttl if ttl else None)

    def refresh(self, key, ttl):
        with self._lock:
            if key in self._data:
                v, _ = self._data[key]
                self._data[key] = (v, time.time() + ttl)

    def get_prefix(self, prefix):
        now = time.time()
        with self._lock:
            items = []
            for k, (v, exp) in sorted(self._data.items()):
                if k.startswith(prefix) and (exp is None or exp > now):
                    items.append((k, v))
            return items

    def delete(self, key):
        with self._lock:
            self._data.pop(key, None)


class ElasticManager:
    """Membership + scale detection (manager.py:130).

    Each node PUTs `{prefix}/{host}` with a TTL lease and heartbeats it; the
    observed member set defines the cluster. When membership changes inside
    the [np_min, np_max] window the manager reports RESTART with rewritten
    endpoints (DISTRIBUTED_TRAINER_ENDPOINTS in the reference); outside the
    window it HOLDs.
    """

    def __init__(self, host, np_range, store=None, job_id="default",
                 ttl=10, heartbeat_interval=3):
        self.host = host
        if isinstance(np_range, str) and ":" in np_range:
            lo, hi = np_range.split(":")
            self.np_min, self.np_max = int(lo), int(hi)
        else:
            n = int(np_range)
            self.np_min = self.np_max = n
        self.store = store if store is not None else LocalKVStore()
        self.prefix = f"/paddle_tpu/elastic/{job_id}/nodes"
        self.ttl = ttl
        self.heartbeat_interval = heartbeat_interval
        self._stop = threading.Event()
        self._hb_thread = None
        self._last_members = None

    # -- membership ----------------------------------------------------------
    def register(self):
        self.store.put(f"{self.prefix}/{self.host}", self.host, ttl=self.ttl)

    def start_heartbeat(self):
        try:
            self.register()
        except Exception as e:  # store down at startup: the beat loop
            self._log_hb_error(e)  # below keeps retrying until it joins

        def beat():
            while not self._stop.is_set():
                try:
                    # re-REGISTER rather than refresh: if the lease expired
                    # during a store outage, refresh would be a no-op and
                    # the node would stay dropped forever (manager.py:245
                    # re-registers on lease loss for the same reason)
                    if self._stop.is_set():
                        break  # narrow the stop()/delete vs in-flight-put
                    self.register()  # resurrection race to one check-gap
                    self._hb_failures = 0
                except Exception as e:
                    # transient etcd failure: keep beating — the TTL gives
                    # us ttl seconds of outage before membership drops
                    self._log_hb_error(e)
                self._stop.wait(self.heartbeat_interval)

        self._hb_thread = threading.Thread(target=beat, daemon=True)
        self._hb_thread.start()

    _hb_failures = 0

    def _log_hb_error(self, e):
        """First failure of an outage is reported (a PERMANENT store/config
        error would otherwise be silently swallowed into a membership
        drop); repeats stay quiet until the store recovers."""
        self._hb_failures += 1
        if self._hb_failures == 1:
            import logging

            logging.getLogger(__name__).warning(
                "elastic heartbeat to the membership store failed "
                "(node %s): %r — retrying every %ss; membership drops "
                "after ttl=%ss of outage", self.host, e,
                self.heartbeat_interval, self.ttl)

    def stop(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        try:
            self.store.delete(f"{self.prefix}/{self.host}")
        except Exception as e:
            # best-effort deregistration: an unreachable store must not
            # turn shutdown into a crash — the TTL lease expires the key
            import logging

            logging.getLogger(__name__).info(
                "elastic deregistration skipped (store unreachable: %r); "
                "the TTL lease will expire the membership key", e)

    def members(self):
        return [v for _, v in self.store.get_prefix(self.prefix)]

    # -- scale decisions -----------------------------------------------------
    def pod_status(self):
        members = self.members()
        n = len(members)
        if n < self.np_min:
            return ElasticStatus.HOLD
        changed = (self._last_members is not None
                   and set(members) != set(self._last_members))
        self._last_members = members
        if changed:
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED if n >= self.np_min else ElasticStatus.HOLD

    def endpoints(self, base_port=8091):
        return [f"{h}:{base_port}" for h in sorted(self.members())]

    def wait_for_np(self, timeout=60):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.np_min <= len(self.members()) <= self.np_max:
                return True
            time.sleep(0.2)
        return False


class ElasticController:
    """The manager.py main loop (reference manager.py:130 Watch/launcher
    coupling): wait for the member window, launch workers with the
    current endpoints, watch both the processes and the membership, and
    on a scale event kill + relaunch with rewritten endpoints.

        ctl = ElasticController(manager, launch_fn)
        rc = ctl.run()

    launch_fn(endpoints) -> list[subprocess.Popen]. Returns the final
    exit code once a life finishes with no membership change (COMPLETED)
    or the restart budget is exhausted.

    Restart budgeting: `max_restarts` bounds CRASH restarts only — a
    worker dying is a failure the budget exists to cap. Scale-event
    relaunches (RESTART/HOLD membership changes) are the system working as
    designed; they are tracked separately (`scale_relaunches`) and never
    consume the crash budget, so a long-lived elastic job that grows and
    shrinks many times still has its full failure budget when a real crash
    arrives. `max_scale_relaunches` (default None = unbounded) caps them
    independently for tests/safety valves.

    `on_restart(info)` is the resume hook: invoked on every RESTART path
    (worker crash or scale event) after the old life is terminated and
    before the relaunch, with {"reason", "restarts", "endpoints"} — plus
    "resume_step" (newest valid checkpoint step, or None) when a
    `checkpoint_manager` (robustness.CheckpointManager) is given, so the
    relaunch command line can pin the exact resume point instead of every
    worker re-deriving it. The relaunched workers restore weights AND
    job_state from that step (robustness.distributed_ft.elastic_resume),
    then prove bucket agreement (agree_bucket_assignment) before their
    first gradient sync — a shrunk group re-derives its bucket layout from
    the same deterministic assignment, and the proof catches a rank that
    resumed from a different step.
    """

    def __init__(self, manager: "ElasticManager", launch_fn,
                 poll_interval: float = 0.3, max_restarts: int = 10,
                 on_restart=None, checkpoint_manager=None,
                 max_scale_relaunches=None, reshard_on_scale=True):
        self.manager = manager
        self.launch_fn = launch_fn
        self.poll_interval = float(poll_interval)
        self.max_restarts = int(max_restarts)
        self.max_scale_relaunches = (None if max_scale_relaunches is None
                                     else int(max_scale_relaunches))
        self.on_restart = on_restart
        self.checkpoint_manager = checkpoint_manager
        # elastic resharding (ISSUE 10): before (re)launching a life whose
        # member count differs from the newest sharded checkpoint's world,
        # transform that checkpoint N→M host-side
        # (distributed/sharding/reshard.py) so a stage-2/3 job CONTINUES
        # after rank loss instead of refusing the geometry-drifted resume
        self.reshard_on_scale = bool(reshard_on_scale)
        self.lives = []  # endpoint list per launched life (observability)
        self.restart_events = []  # info dict per RESTART (observability)
        self.reshard_events = []  # one dict per checkpoint reshard
        self.crash_restarts = 0       # consume max_restarts
        self.scale_relaunches = 0     # budgeted separately (or not at all)

    def _resume_step(self):
        """Newest valid checkpoint step to resume the next life from. Waits
        out any in-flight async save first — killing a life must not orphan
        a checkpoint that is one fsync from committed."""
        if self.checkpoint_manager is None:
            return None
        try:
            self.checkpoint_manager.wait()
            valid = self.checkpoint_manager.valid_steps()
            return valid[-1] if valid else None
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "elastic: could not derive resume step (%r); workers will "
                "fall back to load_latest()", e)
            return None

    def _fire_restart(self, reason, restarts, endpoints):
        info = {"reason": reason, "restarts": restarts,
                "endpoints": list(endpoints)}
        if self.checkpoint_manager is not None:
            info["resume_step"] = self._resume_step()
        self.restart_events.append(info)
        from ....observability import get_event_log
        from ....observability.metrics import get_registry

        get_registry().counter(
            "elastic_restarts_total", help="elastic job relaunches",
            labels=("reason",)).labels(reason=reason).inc()
        get_event_log().warning(
            "elastic", "restarting job", reason=reason, restarts=restarts,
            endpoints=list(endpoints), resume_step=info.get("resume_step"))
        if self.on_restart is not None:
            try:
                self.on_restart(info)
            except Exception as e:
                import logging

                logging.getLogger(__name__).warning(
                    "elastic resume hook failed (%r); relaunching anyway", e)

    def _maybe_reshard(self, world):
        """Shrink/grow restart path: if the newest valid checkpoint is
        SHARDED at a world other than `world`, reshard it in place so the
        relaunched workers load matching geometry (each worker could also
        transform independently via load_sharded(allow_reshard=True); the
        controller doing it once keeps the relaunch N reads cheaper).
        Failures log and fall through — the workers' allow_reshard path is
        the backstop."""
        if not self.reshard_on_scale or self.checkpoint_manager is None:
            return None
        try:
            self.checkpoint_manager.wait()
            step = None
            manifest = None
            for s in sorted(self.checkpoint_manager.steps(), reverse=True):
                m = self.checkpoint_manager.validate(s)
                if m is not None:
                    step, manifest = s, m
                    break
            if manifest is None or not manifest.get("sharded"):
                return None
            from ...sharding import reshard as _reshard

            payload0 = self.checkpoint_manager.load(step, shard=0)
            from_world = _reshard._sharding_world_of(
                [payload0], manifest["world_size"])
            if from_world == int(world):
                return None
            _reshard.reshard_checkpoint(self.checkpoint_manager, step,
                                        int(world))
            info = {"step": int(step), "from_world": int(from_world),
                    "to_world": int(world)}
            self.reshard_events.append(info)
            return info
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "elastic: checkpoint reshard before relaunch failed (%r); "
                "workers must reshard on load (allow_reshard=True)", e)
            return None

    @staticmethod
    def _terminate(procs):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()

    def run(self, np_timeout: float = 60.0):
        self.manager.start_heartbeat()
        try:
            while True:
                if not self.manager.wait_for_np(timeout=np_timeout):
                    raise TimeoutError(
                        f"cluster never reached np window "
                        f"[{self.manager.np_min}, {self.manager.np_max}]")
                self.manager._last_members = self.manager.members()
                eps = self.manager.endpoints()
                # geometry transform BEFORE the life launches: a shrunk or
                # grown member set must find a matching-world checkpoint
                self._maybe_reshard(len(eps))
                procs = self.launch_fn(eps)
                if procs is None:
                    # launcher not ready for this membership view (e.g.
                    # this node's own registration hasn't landed yet):
                    # hold and re-derive
                    time.sleep(self.poll_interval)
                    continue
                self.lives.append(eps)
                while True:
                    rcs = [p.poll() for p in procs]
                    if all(r == 0 for r in rcs):
                        return 0
                    if any(r is not None and r != 0 for r in rcs):
                        # a worker crashed while peers may hang in a
                        # collective: kill the life and relaunch it
                        # (elastic fault tolerance), like
                        # watch_local_procs' terminate-the-rest. Only
                        # crashes consume the max_restarts budget.
                        self._terminate(procs)
                        self.crash_restarts += 1
                        if self.crash_restarts > self.max_restarts:
                            return next(r for r in rcs if r)
                        self._fire_restart("crash", self.crash_restarts,
                                           eps)
                        break
                    status = self.manager.pod_status()
                    if status in (ElasticStatus.RESTART,
                                  ElasticStatus.HOLD):
                        # scale event (join or TTL-dropped death): kill
                        # this life, rewrite endpoints, relaunch. This is
                        # elasticity working, not a failure — it must NOT
                        # eat the crash budget (a job that scaled N times
                        # would otherwise die on its first real crash).
                        self._terminate(procs)
                        self.scale_relaunches += 1
                        if (self.max_scale_relaunches is not None
                                and self.scale_relaunches
                                > self.max_scale_relaunches):
                            return 1
                        self._fire_restart("scale", self.scale_relaunches,
                                           eps)
                        break
                    time.sleep(self.poll_interval)
        finally:
            self.manager.stop()

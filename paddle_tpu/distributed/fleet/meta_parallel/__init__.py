"""meta_parallel (reference: fleet/meta_parallel/) — model wrappers per
parallel mode + the parallel layer library."""
from .parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .parallel_layers.pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .parallel_layers.random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
from .meta_parallel_base import MetaParallelBase  # noqa: F401
from .model_wrappers import PipelineParallel, ShardingParallel, TensorParallel  # noqa: F401

"""MetaParallelBase (reference: fleet/meta_parallel/meta_parallel_base.py)."""
from __future__ import annotations

from ....nn.layer.layers import Layer


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

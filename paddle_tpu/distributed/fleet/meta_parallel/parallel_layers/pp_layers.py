"""Pipeline layer descriptions.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py (LayerDesc/
SharedLayerDesc/PipelineLayer, 424 LoC) — partitions a LayerDesc list across
pp ranks, with p2p send/recv between stages at runtime.

TPU-native: two modes.
1. **Compatibility mode (this class)**: the full layer list is materialized in
   the single SPMD program; stage boundaries become sharding hints. Correct for
   any LayerDesc list; no pipelining overlap.
2. **Scan mode (used by the GPT flagship, models/gpt.py)**: homogeneous blocks
   are stacked on a leading 'layers' dim sharded over the 'pipe' mesh axis and
   executed with lax.scan — stage memory is distributed, and XLA overlaps the
   per-stage collective with compute. Ring-schedule 1F1B with ppermute is the
   planned upgrade (SURVEY.md §7 hard parts).
"""
from __future__ import annotations

from .....nn.layer.container import LayerList
from .....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """reference pp_layers.py PipelineLayer.

    All stages live in the one SPMD program; `_loss_fn` and `seg_method` match
    the reference API. `compute_loss` is used by PipelineParallel.train_batch.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self.descs = list(layers)
        built = []
        self._shared = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(_SharedRef(self._shared[d.layer_name], d.forward_func))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif callable(d) and not isinstance(d, Layer):
                built.append(_FnLayer(d))
            else:
                built.append(d)
        self.run_function = LayerList(built)

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x

    def compute_loss(self, output, *labels):
        if self._loss_fn is None:
            return output
        return self._loss_fn(output, *labels)

    def get_stage_from_index(self, layer_idx):
        n = len(self.run_function)
        per = max(1, n // self._num_stages)
        return min(layer_idx // per, self._num_stages - 1)


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _SharedRef(Layer):
    def __init__(self, target, forward_func):
        super().__init__()
        # bypass Layer.__setattr__: weights stay owned (and registered) by the
        # first instance only, so tied params appear once in parameters()
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_forward_func", forward_func)

    def forward(self, *args, **kwargs):
        if self._forward_func is not None:
            return self._forward_func(self._target, *args, **kwargs)
        return self._target(*args, **kwargs)

"""Tensor-parallel layers.

Reference: fleet/meta_parallel/parallel_layers/mp_layers.py:30,97,170,249
(VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear /
ParallelCrossEntropy) built on _c_identity/_c_concat/_mp_allreduce ops.

TPU-native inversion: each layer owns the FULL logical weight annotated with a
PartitionSpec over the 'model' mesh axis; GSPMD shards the parameter, and the
matmul's contraction pattern makes XLA emit exactly the Megatron collectives
(column: no comm forward, allreduce backward; row: allreduce forward). The
explicit _c_* ops dissolve into sharding constraints. Eager single-device
behavior is identical to plain Linear/Embedding, so mp_degree=1 parity is free.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....framework.autograd import call_op
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .... import mesh as mesh_mod


def _constrain(tensor, *spec):
    """Apply a sharding constraint when tracing under a mesh; no-op eagerly."""
    m = mesh_mod.get_mesh()
    if m is None or not isinstance(tensor._value, jax.core.Tracer):
        return tensor
    sh = NamedSharding(m, P(*spec))
    return call_op(lambda v: jax.lax.with_sharding_constraint(v, sh), tensor,
                   op_name="shard_constraint")


class VocabParallelEmbedding(Layer):
    """reference mp_layers.py:30 — vocab-sharded embedding (c_embedding op).
    Weight sharded over rows ('model'); XLA turns the gather into a sharded
    lookup + AllReduce."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02),
            dist_spec=P("model", None),
        )

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """reference mp_layers.py:97 — weight [in, out] sharded on out ('model').
    gather_output=False leaves activations sharded on the feature dim."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
            dist_spec=P(None, "model"),
        )
        self.bias = (
            self.create_parameter(shape=[out_features], attr=None, is_bias=True,
                                  dist_spec=P("model"))
            if has_bias else None
        )

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(y)  # replicated
        return _constrain(y, *([None] * (y.ndim - 1) + ["model"]))


class RowParallelLinear(Layer):
    """reference mp_layers.py:170 — weight [in, out] sharded on in ('model');
    XLA inserts the forward AllReduce from the contraction."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
            dist_spec=P("model", None),
        )
        self.bias = (
            self.create_parameter(shape=[out_features], attr=None, is_bias=True)
            if has_bias else None
        )

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, *([None] * (x.ndim - 1) + ["model"]))
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y)


class ParallelCrossEntropy(Layer):
    """reference mp_layers.py:249 (c_softmax_with_cross_entropy op): softmax
    over a vocab-sharded logits dim. GSPMD computes the sharded logsumexp with
    the same comm pattern from the plain formula."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)

"""TP-consistent RNG tracker (reference:
fleet/meta_parallel/parallel_layers/random.py — RNGStatesTracker keeping
'global_seed' (differs across mp ranks) and 'local_seed' (same) streams for
dropout determinism). Implementation lives in framework.random; re-exported
here at the reference's path."""
from .....framework.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)

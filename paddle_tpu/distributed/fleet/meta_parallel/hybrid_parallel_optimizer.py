"""HybridParallelOptimizer (reference:
fleet/meta_parallel/hybrid_parallel_optimizer.py:170 — wraps the inner
optimizer with hybrid-aware grad clip + mp/pp grad sync).

TPU-native: gradient synchronization across dp/sharding is the compiler's job
(GSPMD emits the reduce from sharding specs), so this wrapper only needs to
(a) forward the Optimizer protocol and (b) keep clip semantics global across
the whole (sharded) gradient — which the inner clip already computes globally
because full logical grads flow through the compiled step. That claim is
pinned by tests/test_hybrid_clip_parity.py: the post-clip update matches a
single-device oracle under mp2, sharding2 stage-3, and the pipe2 1F1B
grad_fn path (whose grads pipeline_1f1b pre-reduces over pipe/data before
the TrainStep clips them).
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    """reference: fleet/meta_parallel/hybrid_parallel_gradscaler.py."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._scaler, item)

"""TensorParallel / ShardingParallel / PipelineParallel model wrappers.

Reference: fleet/meta_parallel/{tensor_parallel.py,sharding_parallel.py,
pipeline_parallel.py}. Under GSPMD the first two are parameter-placement
wrappers (sharding specs already live on the parameters); PipelineParallel
additionally owns the micro-batch schedule (train_batch) — see
pipeline_parallel notes in pp_layers for the shard_map-based 1F1B design.
"""
from __future__ import annotations

import numpy as np

from ....framework.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


def _apply_indices(layer, idxs, t):
    """Run one stage's slice of a PipelineLayer's layer list."""
    for i in idxs:
        t = layer.run_function[i](t)
    return t


class TensorParallel(MetaParallelBase):
    """reference: tensor_parallel.py — its _prepare_for_model broadcasts
    every parameter over the mp group so ranks start identical. Under the
    single-controller SPMD design parameters are logically global, so the
    equivalent guarantee is a VERIFICATION: every device holding the same
    logical slice of a parameter must hold identical values at wrap time.
    Divergence (e.g. per-process seeds drifting in a multi-process run)
    would otherwise be resolved silently by whichever replica XLA happens
    to read — exactly the wrongness the reference's broadcast prevents —
    so it fails loudly here."""

    def _prepare_for_model(self):
        self.check_mp_init_consistency()

    def check_mp_init_consistency(self):
        import jax

        from ... import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        if (m is None or "model" not in m.axis_names
                or int(m.shape["model"]) <= 1):
            return
        multiproc = jax.process_count() > 1
        local_rows = []
        for pi, p in enumerate(self._layers.parameters()):
            arr = getattr(p, "_value", None)
            if arr is None or not hasattr(arr, "addressable_shards"):
                continue
            ndim = getattr(arr, "ndim", 0)
            groups = {}
            for sh in arr.addressable_shards:
                idx = sh.index if sh.index else (slice(None),) * ndim
                key = tuple(
                    (sl.start or 0,
                     sl.stop if sl.stop is not None else arr.shape[d])
                    for d, sl in enumerate(idx))
                groups.setdefault(key, []).append(sh)
            for key, shards in groups.items():
                d0 = np.asarray(shards[0].data)
                for other in shards[1:]:
                    if not np.array_equal(d0, np.asarray(other.data),
                                          equal_nan=True):
                        raise RuntimeError(
                            f"tensor-parallel init divergence: parameter "
                            f"{p.name or pi} slice {key} differs between "
                            f"devices {shards[0].device} and {other.device}"
                            f" — replicas must start identical (the "
                            f"reference broadcasts over the mp group)")
                if multiproc:
                    # nan_to_num: identical NaN patterns must fingerprint
                    # equal, not poison the comparison
                    d64 = np.nan_to_num(d0.astype(np.float64, copy=False),
                                        nan=1.0, posinf=2.0, neginf=-2.0)
                    local_rows.append([
                        float(pi), float(hash(key) % (1 << 52)),
                        float(d64.sum()), float(np.abs(d64).sum()),
                        float((d64 * d64).sum())])
        if multiproc and local_rows:
            # the same logical slice fingerprint must agree on every
            # process that holds a replica of it (SPMD: all processes
            # enumerate params in the same order)
            from jax.experimental import multihost_utils as mh

            local = np.asarray(sorted(local_rows), np.float64)
            gathered = mh.process_allgather(local)
            seen = {}
            for proc, rows in enumerate(np.asarray(gathered)):
                for row in np.atleast_2d(rows):
                    key = (row[0], row[1])
                    fp = tuple(row[2:])
                    prev = seen.setdefault(key, (proc, fp))
                    if not np.allclose(prev[1], fp, rtol=0, atol=0):
                        raise RuntimeError(
                            f"tensor-parallel init divergence across "
                            f"processes {prev[0]} and {proc} on parameter "
                            f"index {int(row[0])} — replicas must start "
                            f"identical (the reference broadcasts over "
                            f"the mp group)")


class ShardingParallel(MetaParallelBase):
    """reference: sharding_parallel.py. ZeRO sharding on TPU = parameter/opt
    state sharding specs over the 'sharding' axis; applied in
    fleet.distributed_model + TrainStep's slot shardings."""

    def _prepare_for_model(self):
        from jax.sharding import PartitionSpec as P

        from ... import mesh as mesh_mod

        stage = int(self._strategy.sharding_configs.get("stage", 1))
        deg = mesh_mod.axis_size("sharding")
        self._grad_comm = None
        if stage >= 2:
            # stage-2 eager grad path: bucketed reduce_scatter + all_gather
            # over the sharding axis (grad_comm.py) — each rank reduces only
            # its own grad shard, the decomposition "Automatic Cross-Replica
            # Sharding of Weight Update in Data-Parallel Training" motivates.
            # With grad_comm_configs["overlap"] the buckets launch on the
            # background lane during backward (distributed/overlap.py).
            from ...collective import new_group
            from ...grad_comm import config_from_strategy
            from ...overlap import communicator_for

            self._grad_comm = communicator_for(
                config_from_strategy(self._strategy, default_codec="bf16"),
                group=new_group(axes=("sharding",)))
        if deg <= 1 or stage < 3:
            return
        # stage 3: shard parameters themselves over the sharding axis (first
        # divisible dim not already sharded). Stages 1/2 shard only opt state /
        # grads, which the compiled step derives from slot shardings.
        for p in self._layers.parameters():
            if p.dist_spec is not None:
                continue
            shape = p._value.shape
            for d, s in enumerate(shape):
                if s % deg == 0 and s >= deg:
                    spec = [None] * len(shape)
                    spec[d] = "sharding"
                    p.dist_spec = P(*spec)
                    break

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        # overlap: arm the upcoming backward so buckets reduce-scatter as
        # they complete; apply_collective_grads() is then the flush barrier
        from ...env import get_world_size

        world = get_world_size()
        if (world > 1 and self._grad_comm is not None
                and hasattr(self._grad_comm, "prepare")):
            self._grad_comm.prepare(
                [p for p in self._layers.parameters()
                 if not p.stop_gradient],
                world=world, use_reduce_scatter=True)
        return out

    def apply_collective_grads(self):
        """Eager ZeRO stage-2 grad sync: each rank reduces only its own
        shard of every bucket (reduce_scatter), then shards re-assemble
        (all_gather) — the bandwidth-optimal ring-allreduce decomposition.
        Under the compiled TrainStep GSPMD derives the same reduce_scatter
        from the slot shardings; this is the multi-process eager analog of
        the reference's sharding_stage2 grad path."""
        from ...env import get_world_size

        if self._grad_comm is None or get_world_size() <= 1:
            return
        self._grad_comm.sync(
            [p for p in self._layers.parameters() if not p.stop_gradient],
            world=get_world_size(), use_reduce_scatter=True)


class PipelineParallel(MetaParallelBase):
    """reference: pipeline_parallel.py:30 — owns micro-batched train_batch.

    TPU-native schedule: with a 'pipe' mesh axis and uniform inter-stage
    shapes, train_batch runs the genuine interleaved 1F1B
    (distributed/pipeline.py pipeline_1f1b) with the heterogeneous layer
    list partitioned into stages via lax.switch; otherwise (no pipe axis,
    or stage-boundary shapes differ, which the lockstep ppermute cannot
    carry) it falls back to the accumulate-steps compiled step, whose
    per-micro-batch fwd+bwd already has the 1F1B memory profile.
    """

    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel requires a PipelineLayer (reference semantics)"
            )
        super().__init__(layers, hcg, strategy)
        self.micro_batches = int(
            strategy.pipeline_configs.get("accumulate_steps", 1)
        )
        self._train_step = None

    def _stage_groups(self, p_deg):
        n = len(self._layers.run_function)
        groups = [[] for _ in range(p_deg)]
        for i in range(n):
            groups[min(self._layers.get_stage_from_index(i),
                       p_deg - 1)].append(i)
        return groups if all(groups) else None

    def _1f1b_blockers(self, p_deg, fm):
        """Reasons the interleaved schedule cannot engage for this layer
        list (each maps to a capability the lockstep shard_map lacks)."""
        from ....nn.layer.common import (
            AlphaDropout, Dropout, Dropout2D, Dropout3D,
        )

        reasons = []
        if self._layers._num_stages != p_deg:
            reasons.append(
                f"num_stages={self._layers._num_stages} != pipe degree "
                f"{p_deg} (the reference requires them equal)")
        if fm.buffers:
            reasons.append(
                "stateful buffers (e.g. BatchNorm running stats) cannot "
                "thread through the tick scan")
        if any(getattr(p, "dist_spec", None) is not None
               for p in fm.params):
            reasons.append(
                "dist_spec-sharded parameters need the scan-mode stacked "
                "path (compat 1F1B passes params replicated)")
        if any(isinstance(l, (Dropout, Dropout2D, Dropout3D, AlphaDropout))
               and getattr(l, "p", 0)
               for _, l in self._layers.named_sublayers()):
            reasons.append("active dropout (no per-tick RNG is plumbed)")
        return reasons

    def _boundaries_uniform(self, groups, x_mb_shape, x_dtype, fm):
        """The SPMD ppermute carries ONE activation shape; stage outputs
        must all match the stage input."""
        import jax

        h = jax.ShapeDtypeStruct(tuple(x_mb_shape), x_dtype)
        try:
            for g in groups:
                def apply(hh, idxs=g):
                    out_vals, _ = fm.call(
                        fm.param_values(), fm.buffer_values(),
                        jax.random.key(0), (hh,), training=True,
                        fn=lambda layer, t: _apply_indices(layer, idxs, t))
                    return out_vals
                out = jax.eval_shape(apply, h)
                if (tuple(out.shape) != tuple(h.shape)
                        or out.dtype != h.dtype):
                    return False
        except Exception:
            return False
        return True

    def _build_1f1b_grad_fn(self, mesh, groups, fm):
        """loss+grads via the interleaved schedule: stage selection by
        lax.switch over the pipe rank (heterogeneous layer lists, unlike
        the scan-mode stacked path)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from ...pipeline import pipeline_1f1b

        micro = self.micro_batches or int(mesh.shape["pipe"])

        def grad_fn(train_p, frozen_p, bvals, key, in_vals, lbl_vals):
            if len(in_vals) != 1 or len(lbl_vals) != 1:
                raise ValueError("pipeline 1F1B takes (x,) and (labels,)")

            def run(pv, fn_inner, *args):
                out_vals, _ = fm.call(
                    fm.merge_values(list(pv), list(frozen_p)),
                    list(bvals), key, args, training=True, fn=fn_inner)
                return out_vals

            def embed_fn(p, r):
                return r  # stage 0 consumes the raw micro-batch directly

            def stage_fn(p, h):
                branches = [
                    (lambda hh, idxs=g:
                     run(p, lambda layer, t, idxs=idxs:
                         _apply_indices(layer, idxs, t), hh))
                    for g in groups
                ]
                return jax.lax.switch(jax.lax.axis_index("pipe"),
                                      branches, h)

            def loss_fn(p, y, lbl):
                out = run(p, lambda layer, yy, ll:
                          layer.compute_loss(yy, ll), y, lbl)
                return out

            specs = jax.tree.map(lambda _: P(), tuple(train_p))
            loss, grads = pipeline_1f1b(
                embed_fn, stage_fn, loss_fn, tuple(train_p),
                in_vals[0], lbl_vals[0], mesh=mesh, param_specs=specs,
                microbatches=micro)
            return loss, list(grads)

        return grad_fn

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ....jit import TrainStep

        # pipeline_configs.schedule_mode (reference pipeline_parallel.py):
        # "1F1B" interleaves fwd/bwd so live activations are O(P);
        # "F-then-B" is GPipe fill-drain with O(M) activations. When the
        # interleaved schedule can't engage (no pipe axis / non-uniform
        # stage boundaries), the accumulate-steps fallback still completes
        # each micro-batch's fwd AND bwd inside one scan tick — the 1F1B
        # memory profile — so F-then-B is never silently worse.
        mode = self._strategy.pipeline_configs.get("schedule_mode", "1F1B")
        if mode not in ("1F1B", "F-then-B"):
            raise ValueError(
                f"unknown pipeline schedule_mode {mode!r}; "
                "expected '1F1B' or 'F-then-B'")
        inputs, labels = data
        if self._train_step is None:
            self._train_step = self._make_step(mode, optimizer, inputs)
        loss = self._train_step((inputs,), (labels,))
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def _make_step(self, mode, optimizer, inputs):
        import warnings

        from ....jit import TrainStep
        from ... import mesh as mesh_mod

        def loss_fn(*outs_and_labels):
            return self._layers.compute_loss(*outs_and_labels)

        mesh = mesh_mod.get_mesh()
        p_deg = (int(mesh.shape["pipe"])
                 if mesh is not None and "pipe" in mesh.axis_names else 1)
        if mode == "1F1B" and p_deg > 1:
            from ....jit.functional import FunctionalModule

            fm = FunctionalModule(self._layers)  # ONE flatten; the grad
            # engine and the checks must share its parameter ordering
            groups = self._stage_groups(p_deg)
            micro = self.micro_batches or p_deg
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            xv = getattr(x, "_value", x)
            mb_shape = (xv.shape[0] // micro,) + tuple(xv.shape[1:])
            blockers = self._1f1b_blockers(p_deg, fm)
            if not blockers and not (groups and self._boundaries_uniform(
                    groups, mb_shape, xv.dtype, fm)):
                blockers.append(
                    "stage boundaries must all carry the same activation "
                    "shape (the SPMD ppermute slot)")
            if not blockers:
                return TrainStep(
                    self._layers, None, optimizer,
                    grad_fn=self._build_1f1b_grad_fn(mesh, groups, fm))
            warnings.warn(
                "pipeline 1F1B cannot engage for this PipelineLayer ("
                + "; ".join(blockers) + ") — falling back to the "
                "accumulate-steps schedule (same memory profile, no "
                "inter-stage overlap)", stacklevel=3)
        return TrainStep(self._layers, loss_fn, optimizer,
                         grad_accum_steps=self.micro_batches)

"""TensorParallel / ShardingParallel / PipelineParallel model wrappers.

Reference: fleet/meta_parallel/{tensor_parallel.py,sharding_parallel.py,
pipeline_parallel.py}. Under GSPMD the first two are parameter-placement
wrappers (sharding specs already live on the parameters); PipelineParallel
additionally owns the micro-batch schedule (train_batch) — see
pipeline_parallel notes in pp_layers for the shard_map-based 1F1B design.
"""
from __future__ import annotations

import numpy as np

from ....framework.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


class TensorParallel(MetaParallelBase):
    """reference: tensor_parallel.py — broadcasts params once in the reference;
    here mp-sharded params are placed by fleet.distributed_model."""


class ShardingParallel(MetaParallelBase):
    """reference: sharding_parallel.py. ZeRO sharding on TPU = parameter/opt
    state sharding specs over the 'sharding' axis; applied in
    fleet.distributed_model + TrainStep's slot shardings."""

    def _prepare_for_model(self):
        from jax.sharding import PartitionSpec as P

        from ... import mesh as mesh_mod

        stage = int(self._strategy.sharding_configs.get("stage", 1))
        deg = mesh_mod.axis_size("sharding")
        if deg <= 1 or stage < 3:
            return
        # stage 3: shard parameters themselves over the sharding axis (first
        # divisible dim not already sharded). Stages 1/2 shard only opt state /
        # grads, which the compiled step derives from slot shardings.
        for p in self._layers.parameters():
            if p.dist_spec is not None:
                continue
            shape = p._value.shape
            for d, s in enumerate(shape):
                if s % deg == 0 and s >= deg:
                    spec = [None] * len(shape)
                    spec[d] = "sharding"
                    p.dist_spec = P(*spec)
                    break


class PipelineParallel(MetaParallelBase):
    """reference: pipeline_parallel.py:30 — owns micro-batched train_batch.

    TPU-native schedule: the PipelineLayer stores stage-stacked parameters;
    the compiled step runs all stages SPMD under shard_map with ppermute
    rotation (collective-permute pipelining). This wrapper drives it with the
    reference's train_batch(data, optimizer, scaler) signature.
    """

    def __init__(self, layers, hcg, strategy):
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel requires a PipelineLayer (reference semantics)"
            )
        super().__init__(layers, hcg, strategy)
        self.micro_batches = int(
            strategy.pipeline_configs.get("accumulate_steps", 1)
        )
        self._train_step = None

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ....jit import TrainStep

        # pipeline_configs.schedule_mode (reference pipeline_parallel.py):
        # "1F1B" interleaves fwd/bwd so live activations are O(P);
        # "F-then-B" is GPipe fill-drain with O(M) activations. In this
        # compat wrapper every micro-batch's fwd AND bwd complete inside one
        # lax.scan tick of TrainStep's accumulation loop, which is exactly
        # the 1F1B memory profile — F-then-B would be strictly worse, so
        # both modes map to the same schedule here. Scan-mode GPT gets the
        # genuine interleaved schedule via models.gpt_1f1b_train_step
        # (distributed/pipeline.py pipeline_1f1b).
        mode = self._strategy.pipeline_configs.get("schedule_mode", "1F1B")
        if mode not in ("1F1B", "F-then-B"):
            raise ValueError(
                f"unknown pipeline schedule_mode {mode!r}; "
                "expected '1F1B' or 'F-then-B'")
        inputs, labels = data
        if self._train_step is None:
            def loss_fn(*outs_and_labels):
                return self._layers.compute_loss(*outs_and_labels)

            self._train_step = TrainStep(self._layers, loss_fn, optimizer,
                                         grad_accum_steps=self.micro_batches)
        loss = self._train_step((inputs,), (labels,))
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

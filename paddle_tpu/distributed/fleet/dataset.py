"""Fleet datasets — file-list ingestion for PS/CTR training.

Reference: python/paddle/distributed/fleet/dataset/dataset.py:341
(InMemoryDataset / QueueDataset over the C++ MultiSlotDataFeed pipelines,
fluid/framework/data_feed.cc): slot-based text records streamed from a file
list, with load_into_memory + local/global shuffle for the in-memory
variant.

TPU-native: records parse host-side into numpy slot arrays; the training
loop consumes batches through the multiprocess DataLoader (io/worker.py) or
directly via iterate(). The C++ pipe_command subprocess protocol is honored
by running the command per file when set.
"""
from __future__ import annotations

import random
import subprocess
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


def _default_parse(line: str):
    """Default MultiSlot text parse: whitespace-separated numbers; ints stay
    ints (sparse slot ids), anything with a '.' becomes float."""
    out = []
    for tok in line.split():
        try:
            out.append(float(tok) if "." in tok or "e" in tok.lower()
                       else int(tok))
        except ValueError:
            out.append(tok)
    return out


class DatasetBase:
    """Shared config surface (reference DatasetBase.set_* methods)."""

    def __init__(self):
        self.filelist: List[str] = []
        self.batch_size = 1
        self.thread_num = 1
        self.use_var: Sequence = []
        self.pipe_command: Optional[str] = None
        self.parse_fn: Callable = _default_parse
        self.drop_last = False

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, parse_fn=None, **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.use_var = use_var or []
        self.pipe_command = pipe_command
        if parse_fn is not None:
            self.parse_fn = parse_fn
        return self

    # reference setter surface
    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_use_var(self, var_list):
        self.use_var = var_list

    def set_pipe_command(self, cmd):
        self.pipe_command = cmd

    def get_filelist(self):
        return list(self.filelist)

    # -- record streaming ---------------------------------------------------
    def _read_file(self, path: str):
        if self.pipe_command:
            proc = subprocess.run(
                f"{self.pipe_command} < {path}", shell=True,
                capture_output=True, text=True, check=True)
            lines = proc.stdout.splitlines()
        else:
            with open(path) as f:
                lines = f.read().splitlines()
        for line in lines:
            line = line.strip()
            if line:
                yield self.parse_fn(line)

    def _stream_records(self):
        for path in self.filelist:
            yield from self._read_file(path)

    @staticmethod
    def _collate(records):
        cols = list(zip(*records))
        out = []
        for col in cols:
            arr = np.asarray(col)
            out.append(arr[:, None] if arr.ndim == 1 else arr)
        return out

    def _batches_from(self, records):
        buf = []
        for rec in records:
            buf.append(rec)
            if len(buf) == self.batch_size:
                yield self._collate(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._collate(buf)


class InMemoryDataset(DatasetBase):
    """reference dataset.py InMemoryDataset: load once, shuffle in memory,
    iterate many epochs."""

    def __init__(self):
        super().__init__()
        self._records: Optional[list] = None

    def load_into_memory(self):
        self._records = list(self._stream_records())

    def get_memory_data_size(self, fleet=None):
        return len(self._records or [])

    def local_shuffle(self, seed=0):
        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        random.Random(seed).shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=None, seed=0):
        """Single-host runtime: global == local shuffle (the reference moves
        records between trainers through the PS; with data already sharded
        per-host by filelist, a local shuffle is the same distribution)."""
        self.local_shuffle(seed=seed)

    def release_memory(self):
        self._records = None

    def iterate(self):
        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        yield from self._batches_from(iter(self._records))

    def slots_shuffle(self, slots):  # CTR feature shuffle: not applicable
        pass


class QueueDataset(DatasetBase):
    """reference dataset.py QueueDataset: stream straight from files, one
    pass, no memory residency."""

    def iterate(self):
        yield from self._batches_from(self._stream_records())

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffling "
            "(reference raises the same)")

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffling")

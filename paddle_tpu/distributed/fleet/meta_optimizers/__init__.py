from .dygraph_optimizer import (  # noqa: F401
    DygraphShardingOptimizer, GradientMergeOptimizer, LocalSGDOptimizer,
)

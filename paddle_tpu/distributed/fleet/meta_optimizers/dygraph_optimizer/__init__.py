"""Dygraph meta-optimizers.

Reference: fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py
(stage-1 ZeRO), gradient_merge_optimizer.py, localsgd_optimizer.py. Under
GSPMD these are thin wrappers: sharding is a layout marker the compiled step
honors; gradient merge is host-side accumulation; LocalSGD averages params
over the data axis every k steps.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["DygraphShardingOptimizer", "GradientMergeOptimizer",
           "LocalSGDOptimizer"]


class DygraphShardingOptimizer:
    """ZeRO stage-1: optimizer-state sharding over the 'sharding' mesh axis
    (reference slices the param list per rank; GSPMD shards the slot arrays)."""

    def __init__(self, hcg=None, user_defined_strategy=None,
                 params=None, inner_optimizer_class=None,
                 inner_optimizer=None, **inner_kw):
        if inner_optimizer is None and inner_optimizer_class is not None:
            inner_optimizer = inner_optimizer_class(parameters=params,
                                                   **inner_kw)
        self._inner_opt = inner_optimizer
        self._inner_opt._slot_shard_axis = "sharding"

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, *a, **kw):
        return self._inner_opt.minimize(loss, *a, **kw)

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)


class GradientMergeOptimizer:
    """Accumulate grads k steps, then apply one update
    (reference: gradient_merge_optimizer.py cond-guarded accumulation)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._count = 0

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._count += 1
        if self._count % self.k_steps != 0:
            return  # keep accumulating: .grad adds up across backwards
        if self.avg and self.k_steps > 1:
            for p in self._inner_opt._parameter_list:
                if p.grad is not None:
                    p.grad._value = p.grad._value / self.k_steps
        self._inner_opt.step()

    def clear_grad(self, *a, **kw):
        if self._count % self.k_steps == 0:
            self._inner_opt.clear_grad(*a, **kw)

    def minimize(self, loss, *a, **kw):
        loss.backward()
        self.step()
        if self._count % self.k_steps == 0:
            self.clear_grad()
        return [], []


class LocalSGDOptimizer:
    """Periodic parameter averaging over the data axis
    (reference: localsgd_optimizer.py)."""

    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        # warm-up boundary: while count <= begin_step the replicas train
        # synchronously (average EVERY step); only after begin_step do
        # they switch to k-step local updates — reference
        # localsgd_optimizer.py cond(step > begin_step, begin_localsgd,
        # communicate)
        self.begin_step = int(begin_step)
        self._count = 0

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()
        self._count += 1
        # warm-up is fully synchronous; afterwards syncs land at
        # begin_step + n*k_steps
        sync = (self._count <= self.begin_step
                or (self._count - self.begin_step) % self.k_steps == 0)
        if sync:
            from ....collective import all_reduce
            from ....env import get_world_size

            ws = get_world_size()
            if ws > 1:
                for p in self._inner_opt._parameter_list:
                    all_reduce(p)
                    p._value = p._value / ws

"""Ring attention — sequence/context parallelism over the 'sep' mesh axis.

NET-NEW vs the reference: SURVEY.md §5 records that shjNT/Paddle has NO
sequence/context parallelism (no ring attention/Ulysses; only chunked p2p
primitives partial_send/recv, operators/collective/partial_*_op.cc, that
nothing composes). This module supplies the capability TPU-natively:

- sequence dim sharded over the 'sep' ICI axis;
- each device holds q/k/v chunks; k/v rotate around the ring via ppermute
  while partial attention accumulates with the online-softmax (flash) update,
  so the full O(s^2) score matrix never materializes on one chip;
- compute of chunk i overlaps the ICI transfer of chunk i+1 (XLA schedules
  the ppermute concurrently with the einsum).

Used by models/gpt.py when config.use_ring_attention and a 'sep' axis exists.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

_NEG = -1e30


def _axes_in(mesh, names):
    kept = tuple(a for a in names if a in mesh.axis_names)
    return kept if kept else None


def _vary_like(inits, refs):
    """Under vma-tracked shard_map (the 1F1B pipeline), fresh-zeros scan
    carries are typed replicated while the loop makes them device-varying;
    pcast them up to the union of the reference operands' vma. In untracked
    regions (check_vma=False, e.g. ring_attention_val's own shard_map) every
    vma reads empty and this is a no-op."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        # pre-vma jax (0.4/0.5): no replication typing exists to fix up
        return inits
    target = set()
    for r in refs:
        target |= set(typeof(r).vma)
    if not target:
        return inits

    def cast(a):
        need = tuple(ax for ax in target if ax not in set(jax.typeof(a).vma))
        return jax.lax.pcast(a, need, to="varying") if need else a

    return jax.tree.map(cast, inits)


def _plain_attention(q, k, v, causal):
    """Single-device causal attention — the shared no-SP fallback (also
    used by ulysses.py)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        keep = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(keep, logits, _NEG)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _flash_ring_ok(shape) -> bool:
    """Use the pallas kernel for the per-chunk attention when on TPU with a
    kernel-friendly chunk length (VERDICT r1 item 3: 'extend [flash] to the
    ring-attention inner block')."""
    from ..framework.target import target_platform

    if target_platform() != "tpu":
        return False
    from ..ops.flash_attention import flash_attention_supported

    return flash_attention_supported(tuple(shape), block=256)


def ring_attention_manual(ql, kl, vl, axis: str, sp: int, causal: bool = True):
    """Ring attention body for code ALREADY inside a shard_map manual region
    over `axis` (used directly by the SPMD pipeline schedule, which owns the
    enclosing shard_map). ql/kl/vl: local [b, s_loc, h, d]; `sp` is the static
    size of the ring axis.

    The per-chunk attention is the pallas flash kernel on TPU (diagonal
    chunk causal, earlier chunks unmasked, later chunks skipped) with chunk
    results merged by their log-sum-exp; elsewhere the einsum online-softmax
    path runs."""
    s_loc = ql.shape[1]
    scale = 1.0 / (ql.shape[-1] ** 0.5)
    my = jax.lax.axis_index(axis)
    q_pos = my * s_loc + jnp.arange(s_loc)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    b, s, h, d = ql.shape

    if _flash_ring_ok(ql.shape):
        return _ring_flash(ql, kl, vl, axis, sp, causal)

    def body(carry, i):
        o, m, l, kc, vc = carry
        src = (my - i) % sp  # ring position the current chunk came from
        logits = jnp.einsum("bqhd,bkhd->bhqk", ql, kc) * scale
        logits = logits.astype(jnp.float32)
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            keep = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(keep[None, None], logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
        kc, vc = jax.lax.ppermute((kc, vc), axis, perm)
        return (o_new, m_new, l_new, kc, vc), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    o0, m0, l0 = _vary_like((o0, m0, l0), (ql, kl, vl))
    (o, m, l, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, kl, vl), jnp.arange(sp))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)


def _ring_flash_forward(ql, kl, vl, axis, sp, causal):
    """Ring forward with the pallas flash kernel per chunk: diagonal chunk
    causal, earlier chunks unmasked, later chunks dropped; chunk outputs
    merged by their log-sum-exp."""
    from ..ops.flash_attention import _fwd, _pick_block

    b, s_loc, h, d = ql.shape
    my = jax.lax.axis_index(axis)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    blk = _pick_block(s_loc, 256)
    qt = jnp.transpose(ql, (0, 2, 1, 3))                     # [b, h, s, d]

    def chunk_flash(kc, vc, diagonal):
        kt = jnp.transpose(kc, (0, 2, 1, 3))
        vt = jnp.transpose(vc, (0, 2, 1, 3))
        out, lse = _fwd(qt, kt, vt, diagonal, blk, blk)
        return out, lse[..., 0]                              # [b,h,s,d],[b,h,s]

    def body(carry, i):
        o, lse_tot, kc, vc = carry
        src = (my - i) % sp
        if causal:
            o_c, lse_c = jax.lax.cond(
                src == my,
                lambda: chunk_flash(kc, vc, True),
                lambda: chunk_flash(kc, vc, False))
            lse_c = jnp.where(src > my, _NEG, lse_c)   # later chunks dropped
        else:
            o_c, lse_c = chunk_flash(kc, vc, False)
        new_tot = jnp.logaddexp(lse_tot, lse_c)
        w_old = jnp.exp(lse_tot - new_tot)[..., None]
        w_new = jnp.exp(lse_c - new_tot)[..., None]
        o = o * w_old + o_c.astype(jnp.float32) * w_new
        kc, vc = jax.lax.ppermute((kc, vc), axis, perm)
        return (o, new_tot, kc, vc), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    o0, lse0 = _vary_like((o0, lse0), (ql, kl, vl))
    (o, _, _, _), _ = jax.lax.scan(body, (o0, lse0, kl, vl), jnp.arange(sp))
    return jnp.transpose(o, (0, 2, 1, 3)).astype(ql.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis, sp, causal):
    return _ring_flash_forward(q, k, v, axis, sp, causal)


def _ring_flash_fwd(q, k, v, axis, sp, causal):
    return _ring_flash_forward(q, k, v, axis, sp, causal), (q, k, v)


def _ring_flash_bwd(axis, sp, causal, res, cot):
    # backward recomputes through the (mathematically identical) einsum ring
    # — the flash kernel accelerates the forward; grads stay exact
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b2, c: _ring_einsum(a, b2, c, axis, sp, causal), q, k, v)
    return vjp(cot)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_einsum(ql, kl, vl, axis, sp, causal):
    """The reference einsum online-softmax ring (used as the flash path's
    backward and as the non-TPU path)."""
    s_loc = ql.shape[1]
    scale = 1.0 / (ql.shape[-1] ** 0.5)
    my = jax.lax.axis_index(axis)
    q_pos = my * s_loc + jnp.arange(s_loc)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    b, s, h, d = ql.shape

    def body(carry, i):
        o, m, l, kc, vc = carry
        src = (my - i) % sp
        logits = jnp.einsum("bqhd,bkhd->bhqk", ql, kc) * scale
        logits = logits.astype(jnp.float32)
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            keep = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(keep[None, None], logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
        kc, vc = jax.lax.ppermute((kc, vc), axis, perm)
        return (o_new, m_new, l_new, kc, vc), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    o0, m0, l0 = _vary_like((o0, m0, l0), (ql, kl, vl))
    (o, m, l, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, kl, vl), jnp.arange(sp))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)


def ring_attention_val(q, k, v, axis: str = "sep", causal: bool = True):
    """Value-level ring attention. q/k/v: [batch, seq, heads, head_dim] with
    seq sharded over `axis`. Returns same shape/sharding. Traceable under jit;
    enters a shard_map manual region over the full mesh."""
    mesh = mesh_mod.get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return _plain_attention(q, k, v, causal)

    sp = mesh.shape[axis]
    batch_ax = _axes_in(mesh, ("data", "sharding"))
    head_ax = _axes_in(mesh, ("model",))
    spec = P(batch_ax, axis, head_ax, None)

    @partial(mesh_mod.compat_shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec)
    def ring(ql, kl, vl):
        return ring_attention_manual(ql, kl, vl, axis, sp, causal=causal)

    return ring(q, k, v)


def ring_attention(q, k, v, causal: bool = True, axis: str = "sep"):
    """Tensor-level API: paddle_tpu.distributed.ring_attention."""
    from ..framework.autograd import call_op

    return call_op(lambda a, b, c: ring_attention_val(a, b, c, axis=axis,
                                                      causal=causal),
                   q, k, v, op_name="ring_attention")

"""Bucketed + quantized gradient communication for data parallelism.

Reference: the C++ Reducer (imperative/reducer.cc) coalesces grads into
~`comm_buffer_size` MB groups and launches one allreduce per group instead of
one per parameter; meta_optimizers/fp16_allreduce_optimizer.py halves the wire
dtype. This module is both, plus an EQuARX-style int8 quantized all-reduce
codec (PAPERS.md): per-bucket abs-max scale (the `quantization/observers.py`
AbsMaxObserver rule), quantize -> sum -> dequantize, with an error-feedback
residual carried across steps so convergence is preserved.

TPU-native shape: buckets are flat jnp buffers and the collectives are the
`distributed/collective.py` functions, so the same codec runs eagerly (host
emulation for multi-process CPU testing) and inside shard_map/pjit traces
(lowering to XLA AllReduce / ReduceScatter over ICI).

Determinism contract: bucket assignment is a pure function of the parameter
traversal order and the grad dtypes/shapes — identical across SPMD ranks by
construction (all ranks enumerate the same model), so ranks always agree on
which collective carries which parameter.

Overlap: `DistributedStrategy.grad_comm_configs["overlap"] = True` (or
`GradCommConfig(overlap=True)`) swaps in
`overlap.OverlappedGradCommunicator` — each bucket's collective launches on
a background lane the moment backward produces its last gradient, instead
of all buckets running serially after backward; `sync()` becomes the flush
barrier. Values are bit-identical to the serial path (the codecs, error
feedback, and bucket assignment here are shared verbatim); only the wall
clock moves. See distributed/overlap.py.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

# the collective module is bound by name (not function) so tests can
# monkeypatch coll.all_reduce / coll.reduce_scatter and be seen here
from . import collective as _coll
from .collective import ReduceOp
from ..framework.tensor import Tensor
from ..observability.metrics import get_registry as _get_registry

# wire-traffic telemetry (ISSUE 3 sweep): what sync() actually put on the
# wire, per codec, plus how full the buckets ran — the counters
# tools/trace_report.py joins against the step-time breakdown's comm row
_m_syncs = _get_registry().counter(
    "grad_comm_syncs_total", help="gradient sync rounds").bind()
_m_coll = _get_registry().counter(
    "grad_comm_collectives_total",
    help="collectives issued by bucketed grad sync", labels=("codec",))
_m_bytes = _get_registry().counter(
    "grad_comm_bytes_total", help="wire bytes moved by grad sync",
    labels=("codec",))
_m_fill = _get_registry().histogram(
    "grad_comm_bucket_fill_ratio",
    help="bucket bytes / bucket cap at sync time",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0, 1.5))

__all__ = [
    "CODECS", "GradCommConfig", "GradBucket", "GradCommunicator",
    "build_buckets", "comm_plan", "config_from_strategy",
]

CODECS = ("fp32", "bf16", "int8")

# wire bytes per fp32 gradient element, by codec (int8 adds a 4-byte
# per-bucket scale, accounted separately)
_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "int8": 1}

_MB = 1024 * 1024


class GradCommConfig:
    """Gradient-communication knobs (DistributedStrategy.grad_comm_configs).

    codec:  'bf16' (default half-traffic wire format; exponent-safe on TPU),
            'fp32' (escape hatch, full-precision wire), or 'int8' (quantized
            all-reduce, 4x less traffic than fp32, error feedback on).
    comm_buffer_size:        target bucket size in MB (reference DataParallel
                             kwarg of the same name).
    last_comm_buffer_size:   cap of the first-reduced bucket (the reference
                             keeps the last backward bucket small so its
                             collective can launch early).
    error_feedback:          carry the int8 quantization residual across
                             steps (no effect for fp32/bf16).
    overlap:                 launch each bucket's collective the moment its
                             last gradient is produced (bucket-ready async
                             sync, distributed/overlap.py) instead of one
                             serial phase after backward. Bit-identical to
                             the serial path; flush() is the step barrier.
    """

    def __init__(self, codec: str = "bf16", comm_buffer_size: float = 25,
                 last_comm_buffer_size: float = 1, error_feedback: bool = True,
                 overlap: bool = False):
        if codec not in CODECS:
            raise ValueError(
                f"unknown grad_comm codec {codec!r}; one of {CODECS}")
        for name, v in (("comm_buffer_size", comm_buffer_size),
                        ("last_comm_buffer_size", last_comm_buffer_size)):
            try:
                ok = float(v) > 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"{name} must be a positive number of MB, got {v!r}")
        self.codec = codec
        self.comm_buffer_size = float(comm_buffer_size)
        self.last_comm_buffer_size = float(last_comm_buffer_size)
        self.error_feedback = bool(error_feedback)
        self.overlap = bool(overlap)

    def __repr__(self):
        return (f"GradCommConfig(codec={self.codec!r}, "
                f"comm_buffer_size={self.comm_buffer_size}, "
                f"last_comm_buffer_size={self.last_comm_buffer_size}, "
                f"error_feedback={self.error_feedback}, "
                f"overlap={self.overlap})")


class GradBucket:
    """One dtype-homogeneous flat communication bucket."""

    __slots__ = ("index", "dtype", "param_indices", "shapes", "numels",
                 "offsets", "size")

    def __init__(self, index: int, dtype: np.dtype):
        self.index = index
        self.dtype = np.dtype(dtype)
        self.param_indices: List[int] = []   # positions in the param list
        self.shapes: List[tuple] = []
        self.numels: List[int] = []
        self.offsets: List[int] = []         # start offset of each param
        self.size = 0                        # total elements in the bucket

    def add(self, param_index: int, shape: Sequence[int]):
        n = int(np.prod(shape)) if len(shape) else 1
        self.param_indices.append(param_index)
        self.shapes.append(tuple(shape))
        self.numels.append(n)
        self.offsets.append(self.size)
        self.size += n

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def signature(self) -> tuple:
        """Rank-agreement fingerprint: identical on every rank iff the
        assignment is deterministic (no ids, no addresses)."""
        return (self.index, str(self.dtype), tuple(self.param_indices),
                tuple(self.shapes), tuple(self.offsets), self.size)

    def __repr__(self):
        return (f"GradBucket(#{self.index}, dtype={self.dtype}, "
                f"params={len(self.param_indices)}, numel={self.size})")


def build_buckets(params, comm_buffer_size: float = 25,
                  last_comm_buffer_size: float = 1,
                  dtypes: Optional[Sequence] = None) -> List[GradBucket]:
    """Assign parameters to dtype-homogeneous flat buckets.

    Parameters are walked in REVERSE traversal order — the order backward
    produces grads — so the first bucket closes (and its collective could
    launch) earliest; its cap is `last_comm_buffer_size` MB, every later
    bucket's is `comm_buffer_size` MB (reference Reducer group semantics).
    `dtypes` optionally overrides the per-param bucketing dtype (grad dtype
    when known; defaults to the param dtype).
    """
    params = list(params)
    if dtypes is None:
        dtypes = [np.dtype(p._value.dtype) for p in params]
    order = list(range(len(params)))[::-1]
    buckets: List[GradBucket] = []
    open_by_dtype = {}
    for pi in order:
        dt = np.dtype(dtypes[pi])
        shape = tuple(params[pi]._value.shape)
        numel = int(np.prod(shape)) if shape else 1
        b = open_by_dtype.get(dt)
        if b is not None:
            # the earliest-closing bucket keeps the small cap so its
            # collective can launch before the rest of backward finishes
            cap_mb = (last_comm_buffer_size if b.index == 0
                      else comm_buffer_size)
            if b.size > 0 and (b.size + numel) * dt.itemsize > cap_mb * _MB:
                b = None
        if b is None:
            b = GradBucket(len(buckets), dt)
            buckets.append(b)
            open_by_dtype[dt] = b
        b.add(pi, shape)
    return buckets


# --------------------------------------------------------------------- codecs
# Pure jnp transforms so they run identically eagerly and in-trace. The int8
# pair is split around the collectives: encode needs the SHARED scale (max of
# the per-rank abs-max), decode needs the summed int payload.

def encode_bf16(flat):
    return flat.astype(jnp.bfloat16)


def decode_bf16(wire, dtype):
    return wire.astype(dtype)


def int8_scale(flat):
    """Per-bucket abs-max scale (AbsMaxObserver rule): one fp32 scalar."""
    return jnp.maximum(jnp.abs(flat).max(), 1e-12).astype(jnp.float32) / 127.0


def int8_encode(flat, scale):
    """Quantize with the (shared) scale -> int8 payload carried as int32 so
    the summation over ranks cannot overflow."""
    q = jnp.clip(jnp.round(flat.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8).astype(jnp.int32)


def int8_decode(q_sum, scale, world, dtype):
    """Dequantize the summed payload back to the grad dtype (AVG)."""
    return (q_sum.astype(jnp.float32) * scale / world).astype(dtype)


def int8_residual(flat, q, scale):
    """Error-feedback residual: what quantization dropped locally."""
    return flat.astype(jnp.float32) - q.astype(jnp.float32) * scale


class GradCommunicator:
    """Coalesced gradient synchronizer.

    sync() runs ONE collective per bucket (two for int8: a scalar MAX for the
    shared scale + the int payload sum; two for the reduce-scatter mode) and
    writes the averaged gradients back through the original per-param views.
    Per-step wire accounting lives in `.stats`:
        {"codec", "n_params", "n_buckets", "collectives", "comm_bytes"}
    """

    def __init__(self, config: Optional[GradCommConfig] = None, group=None):
        self.config = config or GradCommConfig()
        self.group = group
        self._buckets: Optional[List[GradBucket]] = None
        self._bucket_key = None
        self._residuals = {}          # bucket index -> fp32 flat residual
        self.stats = {"codec": self.config.codec, "n_params": 0,
                      "n_buckets": 0, "collectives": 0, "comm_bytes": 0}

    # ------------------------------------------------------------- planning
    def buckets_for(self, params, dtypes=None) -> List[GradBucket]:
        """Build (and cache) the bucket assignment for this param list."""
        key = tuple((tuple(p._value.shape), str(dt)) for p, dt in
                    zip(params, dtypes or [p._value.dtype for p in params]))
        if self._buckets is None or key != self._bucket_key:
            self._buckets = build_buckets(
                params, self.config.comm_buffer_size,
                self.config.last_comm_buffer_size, dtypes=dtypes)
            # drop error-feedback residuals only when the assignment really
            # changed — a fresh communicator whose residuals were just
            # load_state_dict'ed (resume) must keep them through its first
            # bucket build, or a restart silently changes convergence
            if key != self._bucket_key:
                self._residuals.clear()
            self._bucket_key = key
        return self._buckets

    # ------------------------------------------------------------ job state
    def state_dict(self) -> dict:
        """Resume-critical communicator state: the int8 error-feedback
        residuals (cross-step quantization error) keyed by bucket, plus the
        bucket key they belong to. Stored in the checkpoint's job_state
        entry (robustness/distributed_ft.capture_job_state) — without it a
        resumed int8 run silently diverges from the uninterrupted one."""
        return {
            "codec": self.config.codec,
            "error_feedback": self.config.error_feedback,
            "bucket_key": self._bucket_key,
            "residuals": {int(i): np.asarray(r)
                          for i, r in self._residuals.items()},
        }

    def load_state_dict(self, state: dict):
        """Restore state_dict() output. The codec must match — feeding fp32
        residuals into a bf16 run (or dropping int8 residuals) would change
        convergence without any error surfacing."""
        if state.get("codec") != self.config.codec:
            raise ValueError(
                f"grad_comm state codec mismatch: checkpoint has "
                f"{state.get('codec')!r}, communicator runs "
                f"{self.config.codec!r} — resume with the same wire codec")
        self._bucket_key = state.get("bucket_key")
        self._residuals = {int(i): jnp.asarray(r)
                           for i, r in (state.get("residuals") or {}).items()}

    # ----------------------------------------------------------------- sync
    def sync(self, params, world: Optional[int] = None,
             use_reduce_scatter: bool = False):
        """All-reduce (AVG) the `.grad` of every param, bucketed + encoded.

        `world` is the number of replicas the collective averages over
        (defaults to the process world size — the eager multi-process DP
        notion). With `use_reduce_scatter`, each bucket goes through the
        bandwidth-optimal reduce_scatter -> all_gather decomposition so each
        rank reduces only its own shard (the ZeRO stage-2 grad path).
        """
        from ..profiler import RecordEvent

        params = [p for p in params if p.grad is not None]
        if world is None:
            from .env import get_world_size

            world = get_world_size()
        self.stats = {"codec": self.config.codec, "n_params": len(params),
                      "n_buckets": 0, "collectives": 0, "comm_bytes": 0}
        if world <= 1 or not params:
            return
        dtypes = [np.dtype(p.grad._value.dtype) for p in params]
        buckets = self.buckets_for(params, dtypes=dtypes)
        self.stats["n_buckets"] = len(buckets)
        with RecordEvent("comm"):  # the step-time breakdown's comm phase
            for b in buckets:
                reduced = self._sync_bucket(
                    b, self._flatten_bucket(b, params), world,
                    use_reduce_scatter)
                self._scatter_bucket(b, params, reduced)
        self._record_metrics(buckets)

    @staticmethod
    def _flatten_bucket(bucket: GradBucket, params):
        """The bucket's grads as one flat wire buffer. Shared verbatim by
        the serial and overlapped paths — parity depends on both sides
        concatenating identically."""
        if len(bucket.param_indices) == 1:
            return params[bucket.param_indices[0]].grad._value.reshape(-1)
        return jnp.concatenate([params[pi].grad._value.reshape(-1)
                                for pi in bucket.param_indices])

    @staticmethod
    def _scatter_bucket(bucket: GradBucket, params, reduced):
        """Write a reduced flat buffer back through the original per-param
        grad views (inverse of _flatten_bucket)."""
        for pi, off, n, shape in zip(bucket.param_indices, bucket.offsets,
                                     bucket.numels, bucket.shapes):
            g = params[pi].grad
            g._value = reduced[off:off + n].reshape(shape).astype(
                g._value.dtype)

    def _record_metrics(self, buckets):
        """Mirror this sync's stats into the process-global registry (and
        leave one sync summary in the flight-recorder ring)."""
        codec = self.config.codec
        _m_syncs.value += 1
        _m_coll.labels(codec=codec).inc(self.stats["collectives"])
        _m_bytes.labels(codec=codec).inc(self.stats["comm_bytes"])
        from ..observability.flight_recorder import get_flight_recorder

        get_flight_recorder().note(
            "grad_comm", "sync", codec=codec,
            n_buckets=self.stats["n_buckets"],
            collectives=self.stats["collectives"],
            comm_bytes=self.stats["comm_bytes"])
        for b in buckets:
            cap_mb = (self.config.last_comm_buffer_size if b.index == 0
                      else self.config.comm_buffer_size)
            _m_fill.observe(b.nbytes / (cap_mb * _MB))

    def _sync_bucket(self, bucket: GradBucket, flat, world: int,
                     use_reduce_scatter: bool):
        codec = self.config.codec
        if codec == "int8":
            if self.config.error_feedback:
                res = self._residuals.get(bucket.index)
                if res is not None:
                    flat = flat.astype(jnp.float32) + res
            # share the scale: MAX over ranks makes every rank quantize with
            # the same step, so the summed ints dequantize consistently
            scale_t = Tensor(int8_scale(flat), _internal=True)
            _coll.all_reduce(scale_t, op=ReduceOp.MAX, group=self.group)
            scale = scale_t._value
            q = int8_encode(flat, scale)
            if self.config.error_feedback:
                self._residuals[bucket.index] = int8_residual(flat, q, scale)
            q_sum = self._reduce(q, ReduceOp.SUM, use_reduce_scatter, world)
            self.stats["collectives"] += 1  # the scalar scale exchange
            self.stats["comm_bytes"] += 4
            wire_bytes = bucket.size * _WIRE_ITEMSIZE["int8"]
            reduced = int8_decode(q_sum, scale, world, bucket.dtype)
        elif codec == "bf16" and bucket.dtype.itemsize > 2:
            wire = encode_bf16(flat)
            reduced = decode_bf16(
                self._reduce(wire, ReduceOp.AVG, use_reduce_scatter, world),
                bucket.dtype)
            wire_bytes = bucket.size * _WIRE_ITEMSIZE["bf16"]
        else:
            reduced = self._reduce(flat, ReduceOp.AVG, use_reduce_scatter,
                                   world)
            wire_bytes = bucket.size * flat.dtype.itemsize
        n_coll = 2 if use_reduce_scatter else 1
        self.stats["collectives"] += n_coll
        self.stats["comm_bytes"] += wire_bytes * n_coll
        return reduced

    def describe(self) -> list:
        """Human/JSON-friendly bucket layout of the last sync (one row per
        bucket) — what tools/grad_comm_bench.py prints so bucket-assignment
        regressions are visible in the artifact, not just the counts."""
        if not self._buckets:
            return []
        return [{
            "bucket": b.index,
            "dtype": str(b.dtype),
            "n_params": len(b.param_indices),
            "numel": b.size,
            "mb": round(b.nbytes / _MB, 4),
        } for b in self._buckets]

    def __repr__(self):
        return (f"GradCommunicator({self.config!r}, "
                f"buckets={len(self._buckets or [])})")

    def _reduce(self, wire_val, op, use_reduce_scatter: bool, world: int):
        if use_reduce_scatter:
            # each rank reduces only its own shard, then the shards are
            # re-assembled — the ring-allreduce decomposition, but the shard
            # is available between the two halves for sharded optimizers
            n = wire_val.shape[0]
            pad = (-n) % world
            if pad:
                wire_val = jnp.concatenate(
                    [wire_val, jnp.zeros((pad,), wire_val.dtype)])
            t = Tensor(wire_val, _internal=True)
            shard = _coll.reduce_scatter(t, op=op, group=self.group)
            full = _coll.all_gather(None, shard, group=self.group)
            return full._value.reshape(-1)[:n]
        t = Tensor(wire_val, _internal=True)
        _coll.all_reduce(t, op=op, group=self.group)
        return t._value


def config_from_strategy(strategy, comm_buffer_size: float = 25,
                         last_comm_buffer_size: float = 1,
                         default_codec: str = "fp32") -> GradCommConfig:
    """Resolve the wire codec from a DistributedStrategy: grad_comm_configs
    when the grad_comm toggle is on; else bf16 iff fp16_allreduce
    (fp16_allreduce_optimizer.py semantics); else `default_codec` — 'fp32'
    (the grads' own dtype, the seed DataParallel wire) for the DP path,
    'bf16' for the net-new sharded path. The buffer-size arguments are the
    caller's (e.g. DataParallel ctor) defaults, overridden by
    grad_comm_configs when active."""
    if strategy is not None and getattr(strategy, "grad_comm", False):
        gc = strategy.grad_comm_configs
        return GradCommConfig(
            codec=gc["codec"],
            comm_buffer_size=gc["comm_buffer_size_MB"],
            last_comm_buffer_size=gc["last_comm_buffer_size_MB"],
            error_feedback=gc["error_feedback"],
            overlap=gc.get("overlap", False))
    codec = ("bf16" if strategy is not None
             and getattr(strategy, "fp16_allreduce", False)
             else default_codec)
    return GradCommConfig(codec=codec, comm_buffer_size=comm_buffer_size,
                          last_comm_buffer_size=last_comm_buffer_size)


# ---------------------------------------------------------------- planning
def comm_plan(params, config: Optional[GradCommConfig] = None,
              world: int = 2) -> dict:
    """Static wire-traffic plan for one gradient sync of `params`.

    Pure host-side accounting (no collectives run): how many collectives per
    step and how many bytes cross the wire under `config`, next to the
    un-bucketed per-parameter baseline. Used by bench.py's JSON line and
    tools/grad_comm_bench.py.
    """
    config = config or GradCommConfig()
    params = [p for p in params if not p.stop_gradient]
    buckets = build_buckets(params, config.comm_buffer_size,
                            config.last_comm_buffer_size)
    total_numel = sum(b.size for b in buckets)
    grad_bytes = sum(b.nbytes for b in buckets)
    per_elem = _WIRE_ITEMSIZE[config.codec]
    if config.codec == "bf16":
        # bf16 halves only wider-than-16-bit grads; bf16 grads ship as-is
        comm_bytes = sum(b.size * min(per_elem, b.dtype.itemsize)
                         for b in buckets)
    else:
        comm_bytes = total_numel * per_elem
    collectives = len(buckets)
    if config.codec == "int8":
        collectives *= 2                       # + scalar scale exchange
        comm_bytes += 4 * len(buckets)
    return {
        "codec": config.codec,
        "world": int(world),
        "n_params": len(params),
        "n_buckets": len(buckets),
        "total_grad_numel": int(total_numel),
        "grad_bytes": int(grad_bytes),
        "collectives_per_step": int(collectives),
        "comm_bytes_per_step": int(comm_bytes),
        "per_param_collectives": len(params),
        "per_param_comm_bytes": int(grad_bytes),
        "bucket_bound": int(math.ceil(grad_bytes / _MB /
                                      config.comm_buffer_size)
                            + len({b.dtype for b in buckets}) + 1),
    }

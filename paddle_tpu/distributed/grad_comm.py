"""Bucketed + quantized gradient communication for data parallelism.

Reference: the C++ Reducer (imperative/reducer.cc) coalesces grads into
~`comm_buffer_size` MB groups and launches one allreduce per group instead of
one per parameter; meta_optimizers/fp16_allreduce_optimizer.py halves the wire
dtype. This module is both, plus an EQuARX-style int8 quantized all-reduce
codec (PAPERS.md): per-bucket abs-max scale (the `quantization/observers.py`
AbsMaxObserver rule), quantize -> sum -> dequantize, with an error-feedback
residual carried across steps so convergence is preserved.

TPU-native shape: buckets are flat jnp buffers and the collectives are the
`distributed/collective.py` functions, so the same codec runs eagerly (host
emulation for multi-process CPU testing) and inside shard_map/pjit traces
(lowering to XLA AllReduce / ReduceScatter over ICI).

Blockwise codecs (ISSUE 8, EQuARX): `int8_block` / `fp8_block` quantize with
one abs-max scale per `block_size` elements instead of one per bucket —
orders-of-magnitude tighter scales on a ~25MB bucket — and the per-block
scale vector rides a sum-typed exchange alongside the payload (a real packed
wire format fuses both into one transfer; there is NO scalar-MAX host round
trip). Every codec transform here is pure jnp (enforced by analysis rule
T002), so the exact same encode/decode bits run in the eager sync, on the
overlapped lane, and inside a compiled train step (`jit.TrainStep(grad_comm=)`
/ `overlap.sync_async`) where the error-feedback residual is threaded through
as carried state instead of host-side mutation.

Determinism contract: bucket assignment is a pure function of the parameter
traversal order and the grad dtypes/shapes — identical across SPMD ranks by
construction (all ranks enumerate the same model), so ranks always agree on
which collective carries which parameter.

Overlap: `DistributedStrategy.grad_comm_configs["overlap"] = True` (or
`GradCommConfig(overlap=True)`) swaps in
`overlap.OverlappedGradCommunicator` — each bucket's collective launches on
a background lane the moment backward produces its last gradient, instead
of all buckets running serially after backward; `sync()` becomes the flush
barrier. Values are bit-identical to the serial path (the codecs, error
feedback, and bucket assignment here are shared verbatim); only the wall
clock moves. See distributed/overlap.py.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# the collective module is bound by name (not function) so tests can
# monkeypatch coll.all_reduce / coll.reduce_scatter and be seen here
from . import collective as _coll
from .collective import ReduceOp
from ..framework.tensor import Tensor
from ..observability.metrics import get_registry as _get_registry

# wire-traffic telemetry (ISSUE 3 sweep; ISSUE 8 adds the `path` label):
# what sync() actually put on the wire, per codec AND per execution path
# (eager host sync vs inside a compiled step), plus how full the buckets
# ran — the counters tools/trace_report.py joins against the step-time
# breakdown's comm row. The path label is the satellite fix: the traced
# path used to be indistinguishable from (and mis-accounted as) the eager
# one in /metrics.
_m_syncs = _get_registry().counter(
    "grad_comm_syncs_total", help="gradient sync rounds").bind()
_m_coll = _get_registry().counter(
    "grad_comm_collectives_total",
    help="collectives issued by bucketed grad sync",
    labels=("codec", "path"))
_m_bytes = _get_registry().counter(
    "grad_comm_bytes_total", help="wire bytes moved by grad sync",
    labels=("codec", "path"))
_m_fill = _get_registry().histogram(
    "grad_comm_bucket_fill_ratio",
    help="bucket bytes / bucket cap at sync time",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0, 1.5))

__all__ = [
    "CODECS", "BLOCK_CODECS", "GradCommConfig", "GradBucket",
    "GradCommunicator", "build_buckets", "comm_plan",
    "config_from_strategy", "record_sync_metrics",
    "block_absmax", "block_scales", "block_encode", "block_decode",
    "block_residual", "scale_bytes", "traced_reduce_scatter_quantized",
]

CODECS = ("fp32", "bf16", "int8", "int8_block", "fp8_block")
# blockwise codecs: per-block abs-max scales, error feedback supported
BLOCK_CODECS = ("int8_block", "fp8_block")
# codecs that carry a cross-step error-feedback residual
EF_CODECS = ("int8",) + BLOCK_CODECS

# wire bytes per fp32 gradient element, by codec (int8 adds a 4-byte
# per-bucket scale; the blockwise codecs one fp32 scale per block_size
# elements — accounted separately)
_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "int8": 1, "int8_block": 1,
                  "fp8_block": 1}
# largest representable magnitude of the wire format (int8 symmetric /
# float8_e4m3fn max normal)
_QMAX = {"int8_block": 127.0, "fp8_block": 448.0}
# fp8 wire dtype — present from jax 0.4.x via ml_dtypes; gated so the
# config fails loudly (not deep inside a trace) on ancient jax
_FP8_WIRE = getattr(jnp, "float8_e4m3fn", None)

_MB = 1024 * 1024


class GradCommConfig:
    """Gradient-communication knobs (DistributedStrategy.grad_comm_configs).

    codec:  'bf16' (default half-traffic wire format; exponent-safe on TPU),
            'fp32' (escape hatch, full-precision wire), 'int8' (quantized
            all-reduce, 4x less traffic than fp32, ONE abs-max scale per
            bucket shared via a scalar MAX exchange, error feedback on),
            'int8_block' / 'fp8_block' (EQuARX blockwise: one abs-max scale
            per `block_size` elements — far tighter than per-bucket on a
            ~25MB bucket — with the fp32 scale vector riding a sum-typed
            exchange next to the payload instead of a scalar MAX round
            trip; ~4x less traffic than fp32 plus 4/block_size overhead).
            fp8_block writes float8_e4m3fn on the wire (carried wider
            through the summation, like int8's int32 carrier).
    comm_buffer_size:        target bucket size in MB (reference DataParallel
                             kwarg of the same name).
    last_comm_buffer_size:   cap of the first-reduced bucket (the reference
                             keeps the last backward bucket small so its
                             collective can launch early).
    error_feedback:          carry the quantization residual across steps
                             (int8 and the blockwise codecs; no effect for
                             fp32/bf16). In a compiled step the residual is
                             carried state of the jitted function — see
                             jit.TrainStep(grad_comm=).
    overlap:                 launch each bucket's collective the moment its
                             last gradient is produced (bucket-ready async
                             sync, distributed/overlap.py) instead of one
                             serial phase after backward. Bit-identical to
                             the serial path; flush() is the step barrier.
    block_size:              elements per abs-max scale block for the
                             blockwise codecs (default 1024; one fp32 scale
                             per block = 4/block_size bytes/element of wire
                             overhead). Ignored by the other codecs.
    """

    def __init__(self, codec: str = "bf16", comm_buffer_size: float = 25,
                 last_comm_buffer_size: float = 1, error_feedback: bool = True,
                 overlap: bool = False, block_size: int = 1024):
        if codec not in CODECS:
            raise ValueError(
                f"unknown grad_comm codec {codec!r}; one of {CODECS}")
        if codec == "fp8_block" and _FP8_WIRE is None:
            raise RuntimeError(
                "fp8_block needs jax.numpy.float8_e4m3fn (jax >= 0.4 with "
                "ml_dtypes); this jax build has no fp8 wire dtype")
        for name, v in (("comm_buffer_size", comm_buffer_size),
                        ("last_comm_buffer_size", last_comm_buffer_size)):
            try:
                ok = float(v) > 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"{name} must be a positive number of MB, got {v!r}")
        if not isinstance(block_size, (int, np.integer)) or block_size <= 0:
            raise ValueError(
                f"block_size must be a positive int, got {block_size!r}")
        self.codec = codec
        self.comm_buffer_size = float(comm_buffer_size)
        self.last_comm_buffer_size = float(last_comm_buffer_size)
        self.error_feedback = bool(error_feedback)
        self.overlap = bool(overlap)
        self.block_size = int(block_size)

    def __repr__(self):
        return (f"GradCommConfig(codec={self.codec!r}, "
                f"comm_buffer_size={self.comm_buffer_size}, "
                f"last_comm_buffer_size={self.last_comm_buffer_size}, "
                f"error_feedback={self.error_feedback}, "
                f"overlap={self.overlap}, block_size={self.block_size})")


class GradBucket:
    """One dtype-homogeneous flat communication bucket."""

    __slots__ = ("index", "dtype", "param_indices", "shapes", "numels",
                 "offsets", "size")

    def __init__(self, index: int, dtype: np.dtype):
        self.index = index
        self.dtype = np.dtype(dtype)
        self.param_indices: List[int] = []   # positions in the param list
        self.shapes: List[tuple] = []
        self.numels: List[int] = []
        self.offsets: List[int] = []         # start offset of each param
        self.size = 0                        # total elements in the bucket

    def add(self, param_index: int, shape: Sequence[int]):
        n = int(np.prod(shape)) if len(shape) else 1
        self.param_indices.append(param_index)
        self.shapes.append(tuple(shape))
        self.numels.append(n)
        self.offsets.append(self.size)
        self.size += n

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def signature(self) -> tuple:
        """Rank-agreement fingerprint: identical on every rank iff the
        assignment is deterministic (no ids, no addresses)."""
        return (self.index, str(self.dtype), tuple(self.param_indices),
                tuple(self.shapes), tuple(self.offsets), self.size)

    def __repr__(self):
        return (f"GradBucket(#{self.index}, dtype={self.dtype}, "
                f"params={len(self.param_indices)}, numel={self.size})")


def build_buckets(params, comm_buffer_size: float = 25,
                  last_comm_buffer_size: float = 1,
                  dtypes: Optional[Sequence] = None) -> List[GradBucket]:
    """Assign parameters to dtype-homogeneous flat buckets.

    Parameters are walked in REVERSE traversal order — the order backward
    produces grads — so the first bucket closes (and its collective could
    launch) earliest; its cap is `last_comm_buffer_size` MB, every later
    bucket's is `comm_buffer_size` MB (reference Reducer group semantics).
    `dtypes` optionally overrides the per-param bucketing dtype (grad dtype
    when known; defaults to the param dtype).
    """
    params = list(params)
    if dtypes is None:
        dtypes = [np.dtype(p._value.dtype) for p in params]
    order = list(range(len(params)))[::-1]
    buckets: List[GradBucket] = []
    open_by_dtype = {}
    for pi in order:
        dt = np.dtype(dtypes[pi])
        shape = tuple(params[pi]._value.shape)
        numel = int(np.prod(shape)) if shape else 1
        b = open_by_dtype.get(dt)
        if b is not None:
            # the earliest-closing bucket keeps the small cap so its
            # collective can launch before the rest of backward finishes
            cap_mb = (last_comm_buffer_size if b.index == 0
                      else comm_buffer_size)
            if b.size > 0 and (b.size + numel) * dt.itemsize > cap_mb * _MB:
                b = None
        if b is None:
            b = GradBucket(len(buckets), dt)
            buckets.append(b)
            open_by_dtype[dt] = b
        b.add(pi, shape)
    return buckets


# --------------------------------------------------------------------- codecs
# Pure jnp transforms so they run identically eagerly and in-trace. The int8
# pair is split around the collectives: encode needs the SHARED scale (max of
# the per-rank abs-max), decode needs the summed int payload.

def encode_bf16(flat):
    return flat.astype(jnp.bfloat16)


def decode_bf16(wire, dtype):
    return wire.astype(dtype)


def int8_scale(flat):
    """Per-bucket abs-max scale (AbsMaxObserver rule): one fp32 scalar."""
    return jnp.maximum(jnp.abs(flat).max(), 1e-12).astype(jnp.float32) / 127.0


def int8_encode(flat, scale):
    """Quantize with the (shared) scale -> int8 payload carried as int32 so
    the summation over ranks cannot overflow."""
    q = jnp.clip(jnp.round(flat.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8).astype(jnp.int32)


def int8_decode(q_sum, scale, world, dtype):
    """Dequantize the summed payload back to the grad dtype (AVG)."""
    return (q_sum.astype(jnp.float32) * scale / world).astype(dtype)


def int8_residual(flat, q, scale):
    """Error-feedback residual: what quantization dropped locally."""
    return flat.astype(jnp.float32) - q.astype(jnp.float32) * scale


# ----------------------------------------------------------- blockwise codecs
# EQuARX-style blockwise variants: one abs-max scale per `block_size`
# elements. The scale vector is SHARED by summing every rank's local
# per-block abs-max (a sum-typed exchange that a real packed wire format
# fuses into the payload transfer — no scalar MAX round trip); the summed
# abs-max upper-bounds every rank's, so each rank quantizes into range with
# the identical step and the summed integers dequantize consistently. The
# bound is looser than a true MAX by at most `world`x (≤ log2(world) bits of
# the 8/[fp8 mantissa]), which the per-block granularity more than buys back
# versus the per-bucket scale, and error feedback absorbs across steps.
# Every function here is pure jnp (analysis rule T002) so the same bits run
# eagerly and inside a compiled step.

def n_scale_blocks(numel: int, block_size: int) -> int:
    return -(-int(numel) // int(block_size))


def scale_bytes(numel: int, block_size: int) -> int:
    """Wire overhead of the per-block fp32 scale vector, in bytes."""
    return 4 * n_scale_blocks(numel, block_size)


def _as_blocks(flat, block_size: int):
    """(n_blocks, block_size) fp32 view of a flat buffer, zero-padded."""
    n = flat.shape[0]
    nb = n_scale_blocks(n, block_size)
    pad = nb * block_size - n
    flat = flat.astype(jnp.float32)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat.reshape(nb, block_size)


def block_absmax(flat, block_size: int):
    """Per-block abs-max of a flat buffer: the local half of the shared
    scale (fp32 vector of n_blocks entries)."""
    return jnp.abs(_as_blocks(flat, block_size)).max(axis=1)


def block_scales(absmax, codec: str):
    """Quantization step per block from the (summed-over-ranks) abs-max."""
    return jnp.maximum(absmax, 1e-12).astype(jnp.float32) / _QMAX[codec]


def block_encode(flat, scales, block_size: int, codec: str):
    """Blockwise quantize with the shared scales. int8_block returns the
    int8-valued payload carried as int32 (the summation over ranks must not
    wrap); fp8_block returns the float8_e4m3fn-valued payload carried as
    fp32 (same reason — fp8 addition would round away low bits)."""
    q = _as_blocks(flat, block_size) / scales[:, None]
    if codec == "int8_block":
        return jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8) \
            .astype(jnp.int32)
    return q.astype(_FP8_WIRE).astype(jnp.float32)


def block_decode(q_sum, scales, world, dtype, numel: int):
    """Dequantize the summed blockwise payload back to the grad dtype
    (AVG over `world` replicas)."""
    vals = q_sum.astype(jnp.float32) * scales[:, None]
    return (vals.reshape(-1)[:numel] / world).astype(dtype)


def block_residual(flat, q, scales, numel: int):
    """Error-feedback residual of a blockwise encode: the local input minus
    its own dequantized wire value (no world averaging — local error)."""
    deq = (q.astype(jnp.float32) * scales[:, None]).reshape(-1)[:numel]
    return flat.astype(jnp.float32) - deq


def _block_kernel_ops():
    """Resolve the blockwise transform pair at a call site: the pallas TPU
    kernels (ops/pallas/codec.py, ISSUE 13) when FLAGS_kernel_autotune is
    on AND the compile target is TPU, else the pure-jnp reference pair
    above. The jnp pair stays the interpret-mode reference and the
    flag-off path — with the flag unset this returns the exact functions
    every pre-ISSUE-13 guarantee (traced wire bytes, crash→resume parity)
    was proven against. Payload bits are identical either way (the kernel
    equivalence tests pin it); only wall clock moves."""
    from ..framework.flags import flag

    if flag("FLAGS_kernel_autotune"):
        from ..ops.pallas import codec as _pallas_codec

        if _pallas_codec.use_tpu_kernels():
            return _pallas_codec.block_encode, _pallas_codec.block_decode
    return block_encode, block_decode


def traced_reduce_scatter_quantized(flat, axis, world: int,
                                    config: "GradCommConfig",
                                    residual=None):
    """EQuARX §RS, in-trace: blockwise-quantized reduce_scatter followed by
    a REQUANTIZED all_gather — both halves of the ring decomposition ship
    the 1-byte wire format, with each half's fp32 block scales riding its
    own payload. Must be called from inside a shard_map over `axis`.

    RS half: shared scales (summed per-block abs-max, like reduce_bucket),
    integer psum_scatter; each rank dequantizes only its OWNED shard with
    the matching scale slice (the window where a ZeRO-2 sharded optimizer
    consumes the shard). AG half: every rank requantizes its reduced shard
    with fresh LOCAL block scales — no exchange needed, the per-rank scale
    vector is gathered next to the payload — and all ranks decode each
    gathered shard with its sender's scales.

    Returns `(full, shard, new_residual, wire_bytes, collectives)` where
    `full` is the re-assembled reduced buffer (AVG), `shard` this rank's
    dequantized owned shard, and `new_residual` the RS-half error-feedback
    residual (None when `config.error_feedback` is off). The AG half's
    requantization error is not fed back — it never enters the optimizer
    state on the owning rank, matching EQuARX's error model."""
    codec = config.codec
    if codec not in BLOCK_CODECS:
        raise ValueError(
            f"traced_reduce_scatter_quantized needs a blockwise codec, "
            f"got {codec!r}")
    bs = config.block_size
    n = int(flat.shape[0])
    in_dtype = flat.dtype
    # pad so every rank's shard is a whole number of scale blocks
    chunk_blocks = n_scale_blocks(n_scale_blocks(n, world), bs)
    chunk = chunk_blocks * bs
    padded = world * chunk
    x = flat.astype(jnp.float32)
    if residual is not None:
        x = x + residual
    if padded > n:
        x = jnp.concatenate([x, jnp.zeros((padded - n,), jnp.float32)])
    enc, _dec = _block_kernel_ops()
    # ---- RS half: shared blockwise scales, integer payload psum_scatter
    absmax = jax.lax.psum(block_absmax(x, bs), axis)
    scales = block_scales(absmax, codec)
    q = enc(x, scales, bs, codec)
    new_res = None
    if config.error_feedback:
        new_res = block_residual(x[:n], q, scales, n)
    q_shard = jax.lax.psum_scatter(q.reshape(-1), axis,
                                   scatter_dimension=0, tiled=True)
    rank = jax.lax.axis_index(axis)
    shard_scales = jax.lax.dynamic_slice_in_dim(
        scales, rank * chunk_blocks, chunk_blocks)
    shard = (q_shard.reshape(chunk_blocks, bs).astype(jnp.float32)
             * shard_scales[:, None]).reshape(-1) / world
    # ---- AG half: requantize the reduced shard with LOCAL scales; the
    # per-rank scale vectors ride the gathered payload
    s2 = block_scales(block_absmax(shard, bs), codec)
    q2 = enc(shard, s2, bs, codec)
    gq = jax.lax.all_gather(q2.reshape(-1), axis, tiled=False)
    gs = jax.lax.all_gather(s2, axis, tiled=False)
    full = (gq.reshape(world, chunk_blocks, bs).astype(jnp.float32)
            * gs[:, :, None]).reshape(-1)[:n]
    wire_bytes = 2 * (padded * _WIRE_ITEMSIZE[codec]
                      + scale_bytes(padded, bs))
    return (full.astype(in_dtype), shard.astype(in_dtype), new_res,
            wire_bytes, 4)


def record_sync_metrics(codec: str, collectives: int, comm_bytes: int,
                        path: str):
    """One gradient-sync round into the process-global metric families —
    shared by the eager sync, the overlapped lane, and the compiled step
    (jit.TrainStep increments per executed step; trace-time python only
    runs once, so the traced path cannot count itself)."""
    _m_syncs.value += 1
    _m_coll.labels(codec=codec, path=path).inc(collectives)
    _m_bytes.labels(codec=codec, path=path).inc(comm_bytes)


class GradCommunicator:
    """Coalesced gradient synchronizer.

    sync() runs ONE collective per bucket (two for int8: a scalar MAX for the
    shared scale + the int payload sum; two for the blockwise codecs: the
    per-block scale-vector sum + the payload sum; two for the reduce-scatter
    mode) and writes the averaged gradients back through the original
    per-param views. Per-step wire accounting lives in `.stats`:
        {"codec", "path", "n_params", "n_buckets", "collectives",
         "comm_bytes"}
    where `path` is "eager" for a host-side sync and "traced" when the sync
    ran inside a jax trace, and `comm_bytes` is always the ACTUAL wire
    format's bytes (the traced path used to claim the codec's bytes
    unconditionally).
    """

    def __init__(self, config: Optional[GradCommConfig] = None, group=None):
        self.config = config or GradCommConfig()
        self.group = group
        self._buckets: Optional[List[GradBucket]] = None
        self._bucket_key = None
        self._residuals = {}          # bucket index -> fp32 flat residual
        self.stats = {"codec": self.config.codec, "path": "eager",
                      "n_params": 0, "n_buckets": 0, "collectives": 0,
                      "comm_bytes": 0}

    # ------------------------------------------------------------- planning
    def buckets_for(self, params, dtypes=None) -> List[GradBucket]:
        """Build (and cache) the bucket assignment for this param list."""
        key = tuple((tuple(p._value.shape), str(dt)) for p, dt in
                    zip(params, dtypes or [p._value.dtype for p in params]))
        if self._buckets is None or key != self._bucket_key:
            self._buckets = build_buckets(
                params, self.config.comm_buffer_size,
                self.config.last_comm_buffer_size, dtypes=dtypes)
            # drop error-feedback residuals only when the assignment really
            # changed — a fresh communicator whose residuals were just
            # load_state_dict'ed (resume) must keep them through its first
            # bucket build, or a restart silently changes convergence
            if key != self._bucket_key:
                self._residuals.clear()
            self._bucket_key = key
        return self._buckets

    # ------------------------------------------------------------ job state
    def state_dict(self) -> dict:
        """Resume-critical communicator state: the int8 error-feedback
        residuals (cross-step quantization error) keyed by bucket, plus the
        bucket key they belong to. Stored in the checkpoint's job_state
        entry (robustness/distributed_ft.capture_job_state) — without it a
        resumed int8 run silently diverges from the uninterrupted one."""
        return {
            "codec": self.config.codec,
            "error_feedback": self.config.error_feedback,
            "block_size": self.config.block_size,
            "bucket_key": self._bucket_key,
            "residuals": {int(i): np.asarray(r)
                          for i, r in self._residuals.items()},
        }

    def load_state_dict(self, state: dict):
        """Restore state_dict() output. The codec must match — feeding fp32
        residuals into a bf16 run (or dropping int8 residuals) would change
        convergence without any error surfacing."""
        if state.get("codec") != self.config.codec:
            raise ValueError(
                f"grad_comm state codec mismatch: checkpoint has "
                f"{state.get('codec')!r}, communicator runs "
                f"{self.config.codec!r} — resume with the same wire codec")
        ckpt_bs = state.get("block_size")
        if (self.config.codec in BLOCK_CODECS and ckpt_bs is not None
                and int(ckpt_bs) != self.config.block_size):
            raise ValueError(
                f"grad_comm state block_size mismatch: checkpoint has "
                f"{ckpt_bs}, communicator runs {self.config.block_size} — "
                f"a different scale granularity silently changes the "
                f"quantization the residuals were computed against")
        self._bucket_key = state.get("bucket_key")
        self._residuals = {int(i): jnp.asarray(r)
                           for i, r in (state.get("residuals") or {}).items()}

    # ----------------------------------------------------------------- sync
    def sync(self, params, world: Optional[int] = None,
             use_reduce_scatter: bool = False):
        """All-reduce (AVG) the `.grad` of every param, bucketed + encoded.

        `world` is the number of replicas the collective averages over
        (defaults to the process world size — the eager multi-process DP
        notion). With `use_reduce_scatter`, each bucket goes through the
        bandwidth-optimal reduce_scatter -> all_gather decomposition so each
        rank reduces only its own shard (the ZeRO stage-2 grad path).
        """
        from ..profiler import RecordEvent

        params = [p for p in params if p.grad is not None]
        if world is None:
            from .env import get_world_size

            world = get_world_size()
        self.stats = {"codec": self.config.codec, "path": "eager",
                      "n_params": len(params), "n_buckets": 0,
                      "collectives": 0, "comm_bytes": 0}
        if world <= 1 or not params:
            return
        dtypes = [np.dtype(p.grad._value.dtype) for p in params]
        buckets = self.buckets_for(params, dtypes=dtypes)
        self.stats["n_buckets"] = len(buckets)
        with RecordEvent("comm"):  # the step-time breakdown's comm phase
            for b in buckets:
                reduced = self._sync_bucket(
                    b, self._flatten_bucket(b, params), world,
                    use_reduce_scatter)
                self._scatter_bucket(b, params, reduced)
        self._record_metrics(buckets)

    @staticmethod
    def _flatten_bucket(bucket: GradBucket, params):
        """The bucket's grads as one flat wire buffer. Shared verbatim by
        the serial and overlapped paths — parity depends on both sides
        concatenating identically."""
        if len(bucket.param_indices) == 1:
            return params[bucket.param_indices[0]].grad._value.reshape(-1)
        return jnp.concatenate([params[pi].grad._value.reshape(-1)
                                for pi in bucket.param_indices])

    @staticmethod
    def _scatter_bucket(bucket: GradBucket, params, reduced):
        """Write a reduced flat buffer back through the original per-param
        grad views (inverse of _flatten_bucket)."""
        for pi, off, n, shape in zip(bucket.param_indices, bucket.offsets,
                                     bucket.numels, bucket.shapes):
            g = params[pi].grad
            g._value = reduced[off:off + n].reshape(shape).astype(
                g._value.dtype)

    def _record_metrics(self, buckets, path: str = "eager"):
        """Mirror this sync's stats into the process-global registry (and
        leave one sync summary in the flight-recorder ring)."""
        codec = self.config.codec
        record_sync_metrics(codec, self.stats["collectives"],
                            self.stats["comm_bytes"], path)
        from ..observability.flight_recorder import get_flight_recorder

        get_flight_recorder().note(
            "grad_comm", "sync", codec=codec, path=path,
            n_buckets=self.stats["n_buckets"],
            collectives=self.stats["collectives"],
            comm_bytes=self.stats["comm_bytes"])
        for b in buckets:
            cap_mb = (self.config.last_comm_buffer_size if b.index == 0
                      else self.config.comm_buffer_size)
            _m_fill.observe(b.nbytes / (cap_mb * _MB))

    def _sync_bucket(self, bucket: GradBucket, flat, world: int,
                     use_reduce_scatter: bool):
        """Host-managed form of `reduce_bucket`: the error-feedback
        residual comes from / returns to `self._residuals`, and the wire
        accounting lands in `self.stats`. This is the eager sync and
        overlapped-lane entry point; a TRACED caller with an
        error-feedback codec must use `reduce_bucket` directly (storing a
        tracer on self would leak it out of the trace) — sync_async and
        jit.TrainStep do."""
        ef = (self.config.error_feedback and self.config.codec in EF_CODECS)
        residual = self._residuals.get(bucket.index) if ef else None
        reduced, new_res, wire_bytes, n_coll = self.reduce_bucket(
            bucket, flat, world, use_reduce_scatter=use_reduce_scatter,
            residual=residual)
        if new_res is not None:
            if isinstance(new_res, jax.core.Tracer):
                raise RuntimeError(
                    f"grad_comm codec {self.config.codec!r} with error "
                    f"feedback cannot run via sync() inside a trace — the "
                    f"cross-step residual would leak a tracer into host "
                    f"state. Thread it as carried state instead: "
                    f"sync_async(residuals=...) or jit.TrainStep("
                    f"grad_comm=...)")
            self._residuals[bucket.index] = new_res
        self.stats["path"] = ("traced"
                              if isinstance(reduced, jax.core.Tracer)
                              else "eager")
        self.stats["collectives"] += n_coll
        self.stats["comm_bytes"] += wire_bytes
        return reduced

    def reduce_bucket_payload(self, bucket: GradBucket, flat, world: int,
                              residual=None):
        """Blockwise reduce that STOPS at the summed wire payload: returns
        ``(q_sum, scales, new_residual, wire_bytes, collectives)`` without
        dequantizing — the fused dequant+update kernel
        (ops/pallas/fused_update.fused_dequant_update_flat) consumes the
        payload directly, so the decoded gradient never materializes in
        HBM inside a compiled step (ISSUE 13 follow-on, wired by
        jit.TrainStep's ZeRO-2 grad_comm path). The encode half — shared
        scales from the summed per-block abs-max, error feedback, wire
        accounting — is the exact same math as :meth:`reduce_bucket`'s
        blockwise branch; only the decode moves into the kernel."""
        codec = self.config.codec
        if codec not in BLOCK_CODECS:
            raise ValueError(
                f"reduce_bucket_payload needs a blockwise codec, got "
                f"{codec!r}")
        bs = self.config.block_size
        ef = self.config.error_feedback
        if ef and residual is not None:
            flat = flat.astype(jnp.float32) + residual
        enc, _dec = _block_kernel_ops()
        am_t = Tensor(block_absmax(flat, bs), _internal=True)
        _coll.all_reduce(am_t, op=ReduceOp.SUM, group=self.group)
        scales = block_scales(am_t._value, codec)
        q = enc(flat, scales, bs, codec)
        new_res = block_residual(flat, q, scales, bucket.size) if ef \
            else None
        q_flat = q.reshape(-1)
        t = Tensor(q_flat, _internal=True)
        _coll.all_reduce(t, op=ReduceOp.SUM, group=self.group)
        q_sum = t._value.reshape(q.shape)
        wire_bytes = (bucket.size * _WIRE_ITEMSIZE[codec]
                      + scale_bytes(bucket.size, bs))
        return q_sum, scales, new_res, wire_bytes, 2

    def reduce_bucket(self, bucket: GradBucket, flat, world: int,
                      use_reduce_scatter: bool = False, residual=None):
        """Reduce ONE flat bucket under the configured codec — the pure
        core shared verbatim by the eager sync, the overlapped lane, and
        the traced paths (sync_async / jit.TrainStep's compiled step).

        `residual` is the incoming error-feedback residual (or None);
        returns `(reduced, new_residual, wire_bytes, collectives)` where
        `new_residual` is None for codecs without error feedback and
        `wire_bytes` counts the ACTUAL wire format (payload + any scale
        exchange, doubled for the reduce_scatter->all_gather mode)."""
        codec = self.config.codec
        ef = self.config.error_feedback and codec in EF_CODECS
        new_res = None
        if codec == "int8":
            if ef and residual is not None:
                flat = flat.astype(jnp.float32) + residual
            # share the scale: MAX over ranks makes every rank quantize with
            # the same step, so the summed ints dequantize consistently
            scale_t = Tensor(int8_scale(flat), _internal=True)
            _coll.all_reduce(scale_t, op=ReduceOp.MAX, group=self.group)
            scale = scale_t._value
            q = int8_encode(flat, scale)
            if ef:
                new_res = int8_residual(flat, q, scale)
            q_sum = self._reduce(q, ReduceOp.SUM, use_reduce_scatter, world)
            reduced = int8_decode(q_sum, scale, world, bucket.dtype)
            wire_bytes = bucket.size * _WIRE_ITEMSIZE["int8"] + 4
            n_coll = 2  # scalar scale exchange + payload
        elif codec in BLOCK_CODECS:
            if use_reduce_scatter and isinstance(flat, jax.core.Tracer):
                # in-trace ZeRO-2 path: the EQuARX §RS decomposition with
                # a requantized all_gather half (1-byte wire both ways)
                axes = _coll._axes(self.group)
                reduced, _shard, new_res, wire_bytes, n_coll = \
                    traced_reduce_scatter_quantized(
                        flat, axes if len(axes) > 1 else axes[0], world,
                        self.config,
                        residual=residual if ef else None)
                if not ef:
                    new_res = None
                return (reduced.astype(bucket.dtype), new_res, wire_bytes,
                        n_coll)
            bs = self.config.block_size
            if ef and residual is not None:
                flat = flat.astype(jnp.float32) + residual
            # blockwise shared scales: SUM the local per-block abs-max over
            # ranks (the vector rides a sum-typed exchange a packed wire
            # format fuses with the payload — no scalar MAX round trip);
            # the sum bounds every rank's abs-max, so all ranks quantize
            # with the identical per-block step
            enc, dec = _block_kernel_ops()
            am_t = Tensor(block_absmax(flat, bs), _internal=True)
            _coll.all_reduce(am_t, op=ReduceOp.SUM, group=self.group)
            scales = block_scales(am_t._value, codec)
            q = enc(flat, scales, bs, codec)
            if ef:
                new_res = block_residual(flat, q, scales, bucket.size)
            # the (n_blocks, block_size) payload rides the wire flat —
            # _reduce's reduce_scatter padding/reassembly is 1-D (this was
            # a latent eager ZeRO-2 x blockwise-codec crash; the traced RS
            # path above never hit it)
            q_sum = self._reduce(q.reshape(-1), ReduceOp.SUM,
                                 use_reduce_scatter, world).reshape(q.shape)
            reduced = dec(q_sum, scales, world, bucket.dtype,
                          bucket.size)
            wire_bytes = (bucket.size * _WIRE_ITEMSIZE[codec]
                          + scale_bytes(bucket.size, bs))
            n_coll = 2  # scale-vector exchange + payload
        elif codec == "bf16" and bucket.dtype.itemsize > 2:
            wire = encode_bf16(flat)
            reduced = decode_bf16(
                self._reduce(wire, ReduceOp.AVG, use_reduce_scatter, world),
                bucket.dtype)
            wire_bytes = bucket.size * _WIRE_ITEMSIZE["bf16"]
            n_coll = 1
        else:
            reduced = self._reduce(flat, ReduceOp.AVG, use_reduce_scatter,
                                   world)
            wire_bytes = bucket.size * flat.dtype.itemsize
            n_coll = 1
        if use_reduce_scatter:
            # the payload crosses the wire twice (reduce_scatter half +
            # all_gather half) and counts as two collectives
            payload = wire_bytes - (4 if codec == "int8" else 0) \
                - (scale_bytes(bucket.size, self.config.block_size)
                   if codec in BLOCK_CODECS else 0)
            wire_bytes += payload
            n_coll += 1
        return reduced, new_res, wire_bytes, n_coll

    def describe(self) -> list:
        """Human/JSON-friendly bucket layout of the last sync (one row per
        bucket) — what tools/grad_comm_bench.py prints so bucket-assignment
        regressions are visible in the artifact, not just the counts."""
        if not self._buckets:
            return []
        return [{
            "bucket": b.index,
            "dtype": str(b.dtype),
            "n_params": len(b.param_indices),
            "numel": b.size,
            "mb": round(b.nbytes / _MB, 4),
        } for b in self._buckets]

    def __repr__(self):
        return (f"GradCommunicator({self.config!r}, "
                f"buckets={len(self._buckets or [])})")

    def _reduce(self, wire_val, op, use_reduce_scatter: bool, world: int):
        if use_reduce_scatter:
            # each rank reduces only its own shard, then the shards are
            # re-assembled — the ring-allreduce decomposition, but the shard
            # is available between the two halves for sharded optimizers
            n = wire_val.shape[0]
            pad = (-n) % world
            if pad:
                wire_val = jnp.concatenate(
                    [wire_val, jnp.zeros((pad,), wire_val.dtype)])
            t = Tensor(wire_val, _internal=True)
            shard = _coll.reduce_scatter(t, op=op, group=self.group)
            full = _coll.all_gather(None, shard, group=self.group)
            return full._value.reshape(-1)[:n]
        t = Tensor(wire_val, _internal=True)
        _coll.all_reduce(t, op=op, group=self.group)
        return t._value


def config_from_strategy(strategy, comm_buffer_size: float = 25,
                         last_comm_buffer_size: float = 1,
                         default_codec: str = "fp32") -> GradCommConfig:
    """Resolve the wire codec from a DistributedStrategy: grad_comm_configs
    when the grad_comm toggle is on; else bf16 iff fp16_allreduce
    (fp16_allreduce_optimizer.py semantics); else `default_codec` — 'fp32'
    (the grads' own dtype, the seed DataParallel wire) for the DP path,
    'bf16' for the net-new sharded path. The buffer-size arguments are the
    caller's (e.g. DataParallel ctor) defaults, overridden by
    grad_comm_configs when active."""
    if strategy is not None and getattr(strategy, "grad_comm", False):
        gc = strategy.grad_comm_configs
        return GradCommConfig(
            codec=gc["codec"],
            comm_buffer_size=gc["comm_buffer_size_MB"],
            last_comm_buffer_size=gc["last_comm_buffer_size_MB"],
            error_feedback=gc["error_feedback"],
            overlap=gc.get("overlap", False),
            block_size=gc.get("block_size", 1024))
    codec = ("bf16" if strategy is not None
             and getattr(strategy, "fp16_allreduce", False)
             else default_codec)
    return GradCommConfig(codec=codec, comm_buffer_size=comm_buffer_size,
                          last_comm_buffer_size=last_comm_buffer_size)


# ---------------------------------------------------------------- planning
def comm_plan(params, config: Optional[GradCommConfig] = None,
              world: int = 2) -> dict:
    """Static wire-traffic plan for one gradient sync of `params`.

    Pure host-side accounting (no collectives run): how many collectives per
    step and how many bytes cross the wire under `config`, next to the
    un-bucketed per-parameter baseline. Used by bench.py's JSON line and
    tools/grad_comm_bench.py.
    """
    config = config or GradCommConfig()
    params = [p for p in params if not p.stop_gradient]
    buckets = build_buckets(params, config.comm_buffer_size,
                            config.last_comm_buffer_size)
    total_numel = sum(b.size for b in buckets)
    grad_bytes = sum(b.nbytes for b in buckets)
    per_elem = _WIRE_ITEMSIZE[config.codec]
    if config.codec == "bf16":
        # bf16 halves only wider-than-16-bit grads; bf16 grads ship as-is
        comm_bytes = sum(b.size * min(per_elem, b.dtype.itemsize)
                         for b in buckets)
    else:
        comm_bytes = total_numel * per_elem
    collectives = len(buckets)
    if config.codec == "int8":
        collectives *= 2                       # + scalar scale exchange
        comm_bytes += 4 * len(buckets)
    elif config.codec in BLOCK_CODECS:
        collectives *= 2                       # + per-block scale vector
        comm_bytes += sum(scale_bytes(b.size, config.block_size)
                          for b in buckets)
    return {
        "codec": config.codec,
        "world": int(world),
        "n_params": len(params),
        "n_buckets": len(buckets),
        "total_grad_numel": int(total_numel),
        "grad_bytes": int(grad_bytes),
        "collectives_per_step": int(collectives),
        "comm_bytes_per_step": int(comm_bytes),
        "per_param_collectives": len(params),
        "per_param_comm_bytes": int(grad_bytes),
        "bucket_bound": int(math.ceil(grad_bytes / _MB /
                                      config.comm_buffer_size)
                            + len({b.dtype for b in buckets}) + 1),
    }
